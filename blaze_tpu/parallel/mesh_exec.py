"""Mesh execution tier: partition-parallel operators over the device mesh.

PR 1 made one partition cheap (fused single-dispatch pipelines); this tier
makes N partitions simultaneous: every eligible operator executes ALL of
its partitions inside ONE pjit program over `get_mesh()`, one partition
per device on the 'data' axis, with exchange state HBM-resident end to end
(the host touches data only at the mesh boundary - staging in, fetching
out). The reference's exchange operators (shuffle repartition + broadcast,
SURVEY 2/5) map onto the mesh's native collectives: group-by partial
states repartition by key hash over ICI `all_to_all`
(parallel/sharded.DistributedGroupBy), broadcast joins replicate the build
side with one `all_gather` and reduce matches locally.

Operators here (plus MeshGroupByExec in parallel/mesh_ops.py, which
predates this module and shares its helpers):

  MeshPipelineExec       a scan->filter->project chain executed for every
                         source partition at once: N partitions = ONE
                         dispatch instead of N (no collective - purely
                         partition-parallel)
  MeshBroadcastJoinExec  broadcast hash join: small build side replicated
                         over ICI all_gather, probes local per shard,
                         matches reduced locally (unique-build-key inner
                         join, the dimension-table case)

Failure ladder (blaze_tpu/errors.py taxonomy, PR 3): a TRANSIENT mesh
failure propagates so the task-retry tier re-runs the whole mesh program;
anything else degrades to the op's single-device `fallback` plan
(`mesh.degraded` in the metric tree) - and if that in turn exhausts
resources, the existing service path degrades it to the host engine.
Chaos seam: `mesh.exchange` fires before every mesh program launch.

Observability: every mesh run lands a `mesh_execute` span with one
`mesh_device` child span per device (rows in / rows out tags) and a
`mesh.exchange.*` metric family in the query metric tree; the program
launch is counted as a dispatch (`mesh_dispatches` alongside
`dispatches`), so the dispatch-count perf model covers mesh plans too.
Stage anatomy (obs/meshprof.py): every stage additionally splits into
named sub-phases - mesh_trace (AOT lower+compile, pulled AHEAD of the
launch so trace cost is its own phase), mesh_stage_in, mesh_launch,
mesh_sync, mesh_gather - child spans under `mesh_execute` plus an
always-on rollup; the single-flight locks are named `TimedLock`s so
wait:hold lands in the contention report. The chaos seam fires at the
top of mesh_launch: after the program exists, modeling exchange-fabric
faults rather than compile faults (an injected STALL lands in
mesh_launch, not mesh_trace).
"""

from __future__ import annotations

import logging
import time
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:  # older jax exposes it under experimental
    from jax.experimental.shard_map import shard_map

from blaze_tpu.batch import Column, ColumnBatch
from blaze_tpu.errors import ErrorClass, classify
from blaze_tpu.exprs import ir
from blaze_tpu.obs import contention as obs_contention
from blaze_tpu.obs import meshprof
from blaze_tpu.obs import trace as obs_trace
from blaze_tpu.obs.metrics import REGISTRY
from blaze_tpu.ops.base import ExecContext, PhysicalOp
from blaze_tpu.ops.util import concat_batches, ensure_compacted
from blaze_tpu.parallel.mesh import get_mesh
from blaze_tpu.runtime import dispatch
from blaze_tpu.testing import chaos

log = logging.getLogger("blaze_tpu.mesh")

# per-device span tracks in the exported trace: small synthetic tids so
# each device renders as its own row under the query's process
_DEVICE_TID_BASE = 1000
_MESH_TID = 999


# ---------------------------------------------------------------------------
# staging: host partitions -> HBM-resident [n_dev, cap] stacks
# ---------------------------------------------------------------------------


def to_mesh(global_np: np.ndarray, mesh, axis: str = "data"):
    """Place one host array on the mesh, sharded on its leading axis.

    Single-controller: an explicit device_put with the mesh sharding (the
    HBM-residency contract - the pjit consumes shards in place, no
    implicit re-layout). Multi-process SPMD: every rank holds the full
    logical value (callers decode rank-symmetrically), so build the
    global array from each rank's addressable shards."""
    spec = P(axis, *([None] * (global_np.ndim - 1)))
    sharding = NamedSharding(mesh, spec)
    if jax.process_count() > 1:
        return jax.make_array_from_callback(
            global_np.shape, sharding, lambda idx: global_np[idx]
        )
    return jax.device_put(global_np, sharding)


def stack_partitions(child: PhysicalOp, ctx: ExecContext, mesh,
                     axis: str = "data"):
    """Materialize every child partition and stage the columns as
    HBM-resident [n_dev, cap] stacks (one device per partition, zero-
    padded tail devices for children narrower than the mesh).

    Returns (stacked_cols, num_rows_arr, cap, total_rows, host_cols);
    `host_cols` is the pre-device_put [n_dev, cap] numpy stack per
    column, so a consumer that needs input columns BACK on the host
    (the broadcast join's probe output) reuses them instead of paying
    a second boundary crossing. Raises NotImplementedError for data
    the mesh tier does not handle (string columns, materialized
    validity masks) - callers treat that as ineligibility and fall
    back."""
    n_dev = int(mesh.shape[axis])
    if child.partition_count > n_dev:
        raise NotImplementedError(
            "more partitions than devices; use the exchange tier"
        )
    for f in child.schema.fields:
        if f.dtype.is_string_like or f.dtype.is_dictionary_encoded:
            raise NotImplementedError(
                "string columns use the file-shuffle tier"
            )
    per_part = []
    for p in range(child.partition_count):
        b = concat_batches(
            list(child.execute(p, ctx)), schema=child.schema
        )
        b = ensure_compacted(b)
        # fail fast BEFORE materializing the remaining partitions: a
        # nullable input detected here falls back to the original plan,
        # and everything collected so far is sunk cost
        for c in b.columns:
            if c.validity is not None:
                raise NotImplementedError(
                    "mesh tier handles non-nullable columns; nullable "
                    "inputs use the exchange tier"
                )
        per_part.append(b)
    cap = max(max((b.capacity for b in per_part), default=1), 1)
    stacked, host_cols = [], []
    for ci, f in enumerate(child.schema.fields):
        phys = f.dtype.physical_dtype()
        rows = []
        for b in per_part:
            v = np.asarray(b.columns[ci].values)
            if len(v) < cap:
                v = np.pad(v, (0, cap - len(v)))
            rows.append(v)
        for _ in range(n_dev - len(per_part)):
            rows.append(np.zeros(cap, dtype=phys))
        host = np.stack(rows)
        host_cols.append(host)
        stacked.append(to_mesh(host, mesh, axis))
    num_rows = to_mesh(
        np.array(
            [b.num_rows for b in per_part]
            + [0] * (n_dev - len(per_part)),
            dtype=np.int32,
        ),
        mesh, axis,
    )
    # staging accounting: one logical H2D per staged column stack (+1
    # for the row counts) - the mesh analog of the packed-batch H2D
    dispatch.record("h2d_batches", len(stacked) + 1)
    total = sum(b.num_rows for b in per_part)
    return stacked, num_rows, cap, total, host_cols


# ---------------------------------------------------------------------------
# shared observe / chaos / degrade machinery
# ---------------------------------------------------------------------------


def mesh_chaos(op_name: str, n_dev: int, ctx: ExecContext) -> None:
    """The `mesh.exchange` chaos seam: fires before every mesh program
    launch (docs/ROBUSTNESS.md) - one module-attribute check off."""
    if chaos.ACTIVE:
        chaos.fire(
            "mesh.exchange", op=op_name, devices=n_dev,
            task_id=ctx.task_id,
        )


def record_exchange(ctx: ExecContext, kind: str, rows: int,
                    nbytes: int) -> None:
    """One ICI collective in the `mesh.exchange.*` metric family (the
    per-query metric tree) + the process registry."""
    ctx.metrics.add(f"mesh.exchange.{kind}", 1)
    ctx.metrics.add("mesh.exchange.rows", rows)
    ctx.metrics.add("mesh.exchange.bytes", nbytes)
    REGISTRY.inc("blaze_mesh_exchange_total", kind=kind)
    REGISTRY.inc("blaze_mesh_exchange_rows_total", n=rows)


def record_mesh_run(ctx: ExecContext, op_name: str, n_dev: int,
                    t0: float, t1: float,
                    per_device: Sequence[dict],
                    stage: Optional["meshprof.MeshStage"] = None
                    ) -> None:
    """Fold one mesh program execution into the metric tree and (when
    tracing) land a `mesh_execute` span with one `mesh_device` child
    per device - the per-device view of a single SPMD program. With a
    finished meshprof stage, the `mesh_execute` span widens to the full
    stage wall and the named sub-phases land as child spans on their
    own synthetic track (sequential, so the per-track nesting sweep
    stays chrome-clean; the mesh_lower phase may predate the stage -
    the recorder's root-widening invariant absorbs it)."""
    ctx.metrics.add("mesh.runs", 1)
    ctx.metrics.add("mesh.devices", n_dev)
    REGISTRY.inc("blaze_mesh_runs_total", op=op_name)
    if not (obs_trace.ACTIVE and ctx.tracer is not None):
        return
    rec = ctx.tracer
    span_t0 = stage.t0 if stage is not None else t0
    span_t1 = stage.t1 if stage is not None and stage.t1 else t1
    parent = rec.record_span(
        "mesh_execute", span_t0, span_t1,
        parent=obs_trace.current_span(), tid=_MESH_TID,
        op=op_name, devices=n_dev,
    )
    if parent is None:  # span cap
        return
    if stage is not None:
        for name, p0, p1 in stage.phases:
            rec.record_span(
                name, p0, p1, parent=parent,
                tid=meshprof.MESH_SUB_TID, op=op_name,
            )
    for d, tags in enumerate(per_device):
        rec.record_span(
            "mesh_device", t0, t1, parent=parent,
            tid=_DEVICE_TID_BASE + d, device=d, **tags,
        )


def degrade_or_raise(op: PhysicalOp, ctx: ExecContext,
                     e: BaseException) -> None:
    """The mesh failure ladder: TRANSIENT (and cancellation) propagate
    so the task-retry tier re-runs the mesh program; everything else -
    ineligibility discovered at execution, injected faults, resource
    exhaustion inside the mesh program - degrades THIS op to its
    single-device fallback plan. (A fallback that itself exhausts
    resources still reaches the host engine through the service's
    existing degradation path - mesh -> single-device -> host.)"""
    if getattr(op, "fallback", None) is None:
        raise e
    if not isinstance(e, (NotImplementedError, AssertionError)):
        ec = classify(e)
        if ec in (ErrorClass.TRANSIENT, ErrorClass.CANCELLED):
            raise e
    op._use_fallback = True
    op._result = None
    ctx.metrics.add("mesh.degraded", 1)
    REGISTRY.inc("blaze_mesh_degraded_total")
    if obs_trace.ACTIVE:
        obs_trace.event(
            "mesh.degraded", op=type(op).__name__,
            error=str(e)[:200],
        )
    log.warning(
        "%s degrading to single-device fallback: %s",
        type(op).__name__, e,
    )


# ---------------------------------------------------------------------------
# MeshPipelineExec: sharded scan -> filter -> project chains
# ---------------------------------------------------------------------------


class _TracedProgram:
    """Signature-keyed trace state for a mesh program that jits one
    callable: the cacheable holder shape (fleet/program_cache) shared
    by the pipeline and sort ops. `prepare()` returns True only when a
    trace actually ran, so a cache hit re-lowered onto a fresh op
    instance skips the trace AND the retrace accounting."""

    def __init__(self, compile_fn):
        self._compile = compile_fn
        self._fn = None
        self._exec = None  # AOT-compiled executable (mesh_trace phase)
        self._exec_sig = None
        self._traced_sigs = set()

    def prepare(self, *args) -> bool:
        if self._fn is None:
            self._fn = self._compile(len(args))
        sig = meshprof.arg_signature(*args)
        if sig in self._traced_sigs:
            return False
        self._traced_sigs.add(sig)
        try:
            self._exec = self._fn.lower(*args).compile()
            self._exec_sig = sig
        except Exception:  # noqa: BLE001 - no AOT: trace at launch
            self._exec = None
            self._exec_sig = None
        return True

    def __call__(self, *args):
        sig = meshprof.arg_signature(*args)
        if self._exec is not None and self._exec_sig == sig:
            return self._exec(*args)
        return self._fn(*args)


class MeshPipelineExec(PhysicalOp):
    """A filter/project chain over a multi-partition source, executed
    for ALL source partitions in one shard_map program (one partition
    per device). No collective - purely partition-parallel - but the
    N-partitions-for-one-dispatch shape is the mesh tier's raw-speed
    lever for the pipeline stages under an exchange.

    `chain` is the list of Filter/Project nodes from the ROOT down to
    (excluding) the source; each node's bound expressions are evaluated
    per shard against its own input schema. Output: one partition per
    device, live rows compacted host-side at the mesh boundary.
    """

    def __init__(self, root: PhysicalOp, chain: List[PhysicalOp],
                 source: PhysicalOp, mesh=None,
                 fallback: Optional[PhysicalOp] = None):
        from blaze_tpu.ops.filter import FilterExec
        from blaze_tpu.ops.project import ProjectExec

        self.fallback = fallback
        self._use_fallback = False
        self.children = [source]
        self.mesh = mesh or get_mesh()
        self._axis = "data"
        self._schema = root.schema
        for f in self._schema.fields:
            if f.dtype.is_string_like or f.dtype.is_dictionary_encoded:
                raise NotImplementedError(
                    "string outputs use the per-partition tier"
                )
        # bottom-up stage list; every stage is (kind, payload, schema)
        self._stages: List[Tuple[str, object, object]] = []
        for node in reversed(chain):
            if isinstance(node, FilterExec):
                self._stages.append(("filter", node.predicate,
                                     node.schema))
            elif isinstance(node, ProjectExec):
                self._stages.append(("project", list(node.exprs),
                                     node.schema))
            else:
                raise NotImplementedError(
                    f"mesh pipeline cannot shard {type(node).__name__}"
                )
        # structurally-keyed program holder: a fresh lowering of the
        # same chain on the same mesh reuses the traced program
        from blaze_tpu.fleet.program_cache import (
            PROGRAM_CACHE, mesh_cache_key,
        )

        src_schema = source.schema
        cache_key = (
            "mesh.pipeline",
            tuple((f.name, repr(f.dtype), f.nullable)
                  for f in src_schema.fields),
            tuple((kind, repr(payload))
                  for kind, payload, _ in self._stages),
            self._axis,
            mesh_cache_key(self.mesh),
        )
        self._prog = PROGRAM_CACHE.get_or_build(
            cache_key,
            lambda: _TracedProgram(
                lambda nargs: self._compile(nargs - 1)
            ),
        )
        self._result = None
        # single-flight, named so wait:hold lands in the contention
        # report (obs/contention) when the collector is armed
        self._lock = obs_contention.TimedLock("mesh_pipeline")

    @property
    def schema(self):
        return self._schema

    @property
    def partition_count(self) -> int:
        return int(self.mesh.shape[self._axis])

    def describe(self) -> str:
        return (f"MeshPipelineExec[{len(self._stages)} stages, "
                f"{self.partition_count} devices]")

    def _trace_key(self, sig) -> tuple:
        """Logical program identity for re-trace accounting: op kind +
        structural stage expressions + argument signature (repr of the
        IR dataclasses prints structurally)."""
        return (
            "mesh.pipeline",
            tuple(
                (kind, repr(payload)) for kind, payload, _ in self._stages
            ),
            sig,
        )

    # -- program ---------------------------------------------------------
    def _compile(self, ncols: int):
        from blaze_tpu.exprs.eval import DeviceEvaluator

        mesh, axis = self.mesh, self._axis
        src_schema = self.children[0].schema
        stages = self._stages

        def per_shard(num_rows_s, *cols_s):
            cols = [c[0] for c in cols_s]
            nr = num_rows_s[0]
            cap = cols[0].shape[0]
            live = jnp.arange(cap, dtype=jnp.int32) < nr
            cur_schema, cur_cols = src_schema, cols
            for kind, payload, out_schema in stages:
                ev = DeviceEvaluator(
                    cur_schema, [(c, None) for c in cur_cols], cap
                )
                if kind == "filter":
                    live = live & ev.evaluate_predicate(payload)
                else:
                    outs = []
                    for e, _ in payload:
                        v, mm = ev.evaluate(e)
                        if mm is not None:
                            # a masked (nullable) projection output
                            # has no mesh representation yet: trace-
                            # time ineligibility -> fallback
                            raise NotImplementedError(
                                "nullable projection output on the "
                                "mesh tier"
                            )
                        outs.append(v)
                    cur_schema, cur_cols = out_schema, outs
            return tuple(c[None] for c in cur_cols) + (live[None],)

        n_out = len(self._schema) + 1
        fn = shard_map(
            per_shard, mesh=mesh,
            in_specs=(P(axis),) + tuple(P(axis) for _ in range(ncols)),
            out_specs=tuple([P(axis)] * n_out),
        )
        return jax.jit(fn)

    def _run(self, ctx: ExecContext):
        with self._lock:
            if self._result is not None:
                return self._result
            n_dev = self.partition_count
            st = meshprof.stage(
                "mesh.pipeline", n_dev,
                lower_window=getattr(self, "_mesh_lower", None),
            )
            with st.phase("mesh_stage_in"):
                stacked, num_rows, cap, total, host_cols = (
                    stack_partitions(
                        self.children[0], ctx, self.mesh, self._axis
                    )
                )
                st.add_bytes(sum(h.nbytes for h in host_cols))
            with st.phase("mesh_trace"):
                if self._prog.prepare(num_rows, *stacked):
                    meshprof.note_trace(
                        "mesh.pipeline",
                        self._trace_key(meshprof.arg_signature(
                            num_rows, *stacked
                        )),
                    )
            t0 = time.monotonic()
            with st.phase("mesh_launch"):
                mesh_chaos("mesh.pipeline", n_dev, ctx)
                dispatch.record("dispatches")
                dispatch.record("mesh_dispatches")
                outs = self._prog(num_rows, *stacked)
            with st.phase("mesh_sync"):
                outs = jax.block_until_ready(outs)
            with st.phase("mesh_gather"):
                outs = dispatch.device_get(outs)
            t1 = st.finish()
            out_cols, live = outs[:-1], np.asarray(outs[-1])
            nr_host = np.asarray(num_rows)
            record_mesh_run(
                ctx, "mesh.pipeline", n_dev, t0, t1,
                [{"rows_in": int(nr_host[d]),
                  "rows_out": int(live[d].sum())}
                 for d in range(n_dev)],
                stage=st,
            )
            ctx.metrics.add("mesh.pipeline_rows", total)
            self._result = (out_cols, live)
            return self._result

    def execute(self, partition: int, ctx: ExecContext
                ) -> Iterator[ColumnBatch]:
        if self.fallback is not None and not self._use_fallback:
            try:
                self._run(ctx)
            except Exception as e:  # noqa: BLE001 - ladder below
                degrade_or_raise(self, ctx, e)
        if self._use_fallback:
            if partition < self.fallback.partition_count:
                yield from self.fallback.execute(partition, ctx)
            return
        out_cols, live = self._run(ctx)
        idx = np.nonzero(live[partition])[0]
        if len(idx) == 0:
            return
        cols: List[Column] = []
        for arr, f in zip(out_cols, self._schema.fields):
            v = np.asarray(arr[partition])[idx].astype(
                f.dtype.physical_dtype()
            )
            cols.append(Column(f.dtype, v, None, None))
        yield ColumnBatch(self._schema, cols, len(idx))


# ---------------------------------------------------------------------------
# MeshBroadcastJoinExec: ICI-broadcast build side, local probe
# ---------------------------------------------------------------------------


class MeshBroadcastJoinExec(PhysicalOp):
    """Broadcast hash join over the mesh: the (small) build relation is
    replicated to every device with ONE all_gather over ICI, each probe
    partition matches locally, and matches are reduced locally - the
    reference's ArrowBroadcastExchangeExec + CollectLeft probe as a
    single SPMD program (parallel/sharded.DistributedBroadcastJoin).

    Gates (fall back otherwise): INNER equi-join on ONE integer key
    pair, unique build keys (checked at execution - the dimension-table
    contract that keeps output shapes static), fixed-width non-nullable
    columns, probe partitions <= mesh size. Output: one partition per
    device, schema = build fields + probe fields (HashJoinExec INNER
    layout).
    """

    def __init__(self, build: PhysicalOp, probe: PhysicalOp,
                 build_key: int, probe_key: int,
                 mesh=None, fallback: Optional[PhysicalOp] = None):
        self.fallback = fallback
        self._use_fallback = False
        self.children = [build, probe]
        self.mesh = mesh or get_mesh()
        self._axis = "data"
        self.build_key = build_key
        self.probe_key = probe_key
        for side, key in ((build, build_key), (probe, probe_key)):
            dt = side.schema.fields[key].dtype
            if not dt.is_integer:
                raise NotImplementedError(
                    "mesh broadcast join requires integer keys"
                )
        from blaze_tpu.types import Field, Schema

        self._schema = Schema(
            [Field(f.name, f.dtype, f.nullable)
             for f in build.schema.fields]
            + [Field(f.name, f.dtype, f.nullable)
               for f in probe.schema.fields]
        )
        self._join = None
        self._result = None
        # single-flight, named for the contention report
        self._lock = obs_contention.TimedLock("mesh_bcast_join")

    @property
    def schema(self):
        return self._schema

    @property
    def partition_count(self) -> int:
        return int(self.mesh.shape[self._axis])

    def describe(self) -> str:
        return (f"MeshBroadcastJoinExec[{self.partition_count} "
                f"devices]")

    def _shard_build(self, ctx: ExecContext):
        """Collect the build relation and shard it row-wise over the
        mesh [n_dev, b_cap] (the all_gather inside the program re-
        assembles the full relation on every device)."""
        build = self.children[0]
        n_dev = self.partition_count
        batches = [
            b for p in range(build.partition_count)
            for b in build.execute(p, ctx)
        ]
        whole = ensure_compacted(
            concat_batches(batches, schema=build.schema)
        )
        for c in whole.columns:
            if c.validity is not None:
                raise NotImplementedError(
                    "nullable build side uses the per-partition tier"
                )
        n_build = whole.num_rows
        keys = np.asarray(whole.columns[self.build_key].values)[:n_build]
        if len(np.unique(keys)) != n_build:
            raise NotImplementedError(
                "duplicate build keys use the per-partition join"
            )
        b_cap = max(1, -(-max(n_build, 1) // n_dev))
        stacked = []
        for ci, f in enumerate(build.schema.fields):
            v = np.asarray(whole.columns[ci].values)[:n_build]
            pad = n_dev * b_cap - n_build
            v = np.pad(v, (0, pad)).reshape(n_dev, b_cap)
            stacked.append(to_mesh(
                v.astype(f.dtype.physical_dtype()), self.mesh,
                self._axis,
            ))
        rows = np.full(n_dev, b_cap, dtype=np.int32)
        used = n_build
        for d in range(n_dev):
            rows[d] = max(0, min(b_cap, used))
            used -= rows[d]
        dispatch.record("h2d_batches", len(stacked) + 1)
        return stacked, to_mesh(rows, self.mesh, self._axis), n_build

    def _run(self, ctx: ExecContext):
        with self._lock:
            if self._result is not None:
                return self._result
            from blaze_tpu.parallel.sharded import (
                DistributedBroadcastJoin,
            )

            build, probe = self.children
            n_dev = self.partition_count
            st = meshprof.stage(
                "mesh.broadcast_join", n_dev,
                lower_window=getattr(self, "_mesh_lower", None),
            )
            with st.phase("mesh_stage_in"):
                b_cols, b_rows, n_build = self._shard_build(ctx)
                p_cols, p_rows, p_cap, p_total, p_host = (
                    stack_partitions(
                        probe, ctx, self.mesh, self._axis
                    )
                )
                # probe stacks dominate staging; the build side is the
                # small (dimension-table) relation
                st.add_bytes(sum(h.nbytes for h in p_host))
            with st.phase("mesh_trace"):
                if self._join is None:
                    from blaze_tpu.fleet.program_cache import (
                        PROGRAM_CACHE, mesh_cache_key,
                    )

                    cache_key = (
                        "mesh.broadcast_join",
                        tuple((f.name, repr(f.dtype), f.nullable)
                              for f in probe.schema.fields),
                        tuple((f.name, repr(f.dtype), f.nullable)
                              for f in build.schema.fields),
                        self.probe_key, self.build_key, self._axis,
                        mesh_cache_key(self.mesh),
                    )
                    self._join = PROGRAM_CACHE.get_or_build(
                        cache_key,
                        lambda: DistributedBroadcastJoin(
                            self.mesh, probe.schema, build.schema,
                            probe_key=ir.BoundCol(
                                self.probe_key,
                                probe.schema.fields[
                                    self.probe_key
                                ].dtype,
                            ),
                            build_key=ir.BoundCol(
                                self.build_key,
                                build.schema.fields[
                                    self.build_key
                                ].dtype,
                            ),
                            axis=self._axis,
                        ),
                    )
                if self._join.prepare(p_cols, p_rows, b_cols, b_rows):
                    meshprof.note_trace(
                        "mesh.broadcast_join",
                        ("mesh.broadcast_join",
                         repr(self._join.probe_key),
                         repr(self._join.build_key),
                         meshprof.arg_signature(
                             p_cols, p_rows, b_cols, b_rows
                         )),
                    )
            t0 = time.monotonic()
            with st.phase("mesh_launch"):
                mesh_chaos("mesh.broadcast_join", n_dev, ctx)
                dispatch.record("dispatches")
                dispatch.record("mesh_dispatches")
                hit, build_out = self._join(
                    p_cols, p_rows, b_cols, b_rows
                )
            with st.phase("mesh_sync"):
                hit, build_out = jax.block_until_ready(
                    (hit, build_out)
                )
            # ONE batched fetch of the small outputs (hit mask +
            # gathered build values); the probe columns come back from
            # stack_partitions' host-side stacks - staging them in is
            # the only boundary crossing they pay
            with st.phase("mesh_gather"):
                hit, build_out = dispatch.device_get((hit, build_out))
            t1 = st.finish()
            hit = np.asarray(hit)
            nbytes = sum(
                int(np.asarray(c).nbytes) for c in build_out
            )
            record_exchange(ctx, "all_gather", n_build, nbytes)
            nr_host = np.asarray(p_rows)
            record_mesh_run(
                ctx, "mesh.broadcast_join", n_dev, t0, t1,
                [{"rows_in": int(nr_host[d]),
                  "matches": int(hit[d].sum())}
                 for d in range(n_dev)],
                stage=st,
            )
            ctx.metrics.add(
                "mesh_join_matches", int(hit.sum())
            )
            self._result = (
                hit,
                [np.asarray(c) for c in build_out],
                p_host,
            )
            return self._result

    def execute(self, partition: int, ctx: ExecContext
                ) -> Iterator[ColumnBatch]:
        if self.fallback is not None and not self._use_fallback:
            try:
                self._run(ctx)
            except Exception as e:  # noqa: BLE001 - ladder below
                degrade_or_raise(self, ctx, e)
        if self._use_fallback:
            if partition < self.fallback.partition_count:
                yield from self.fallback.execute(partition, ctx)
            return
        hit, build_out, probe_out = self._run(ctx)
        idx = np.nonzero(hit[partition])[0]
        if len(idx) == 0:
            return
        build, probe = self.children
        cols: List[Column] = []
        for arr, f in zip(build_out, build.schema.fields):
            cols.append(Column(
                f.dtype,
                arr[partition][idx].astype(f.dtype.physical_dtype()),
                None, None,
            ))
        for arr, f in zip(probe_out, probe.schema.fields):
            cols.append(Column(
                f.dtype,
                arr[partition][idx].astype(f.dtype.physical_dtype()),
                None, None,
            ))
        yield ColumnBatch(self._schema, cols, len(idx))


# ---------------------------------------------------------------------------
# MeshSortExec: per-shard device sort, host run-merge (ISSUE 20)
# ---------------------------------------------------------------------------


class MeshSortExec(PhysicalOp):
    """A global sort executed as N simultaneous per-shard device sorts
    (one stable lexsort per device inside ONE shard_map program)
    followed by a host k-way merge of the sorted runs - the expensive
    O(n log n) comparisons happen on all devices at once, the host pays
    only the linear merge. Single output partition (a sort is a global
    ordering).

    Gates (fall back otherwise): exactly one ascending key, a
    non-nullable integer bound column, fixed-width non-nullable input
    columns (stack_partitions' contract). Stability matches the
    single-device oracle: ties keep earlier partitions first, and the
    per-shard lexsort is stable within a partition.
    """

    def __init__(self, source: PhysicalOp, keys, fetch=None,
                 mesh=None, fallback: Optional[PhysicalOp] = None):
        self.fallback = fallback
        self._use_fallback = False
        self.children = [source]
        self.mesh = mesh or get_mesh()
        self._axis = "data"
        self._schema = source.schema
        self.fetch = fetch
        if len(keys) != 1:
            raise NotImplementedError(
                "mesh sort takes exactly one key"
            )
        k = keys[0]
        if not k.ascending or not isinstance(k.expr, ir.BoundCol):
            raise NotImplementedError(
                "mesh sort: single ascending bound column only"
            )
        f = source.schema.fields[k.expr.index]
        if not f.dtype.is_integer:
            raise NotImplementedError(
                "mesh sort requires an integer key"
            )
        self.key_index = k.expr.index
        from blaze_tpu.fleet.program_cache import (
            PROGRAM_CACHE, mesh_cache_key,
        )

        cache_key = (
            "mesh.sort",
            tuple((fld.name, repr(fld.dtype), fld.nullable)
                  for fld in self._schema.fields),
            self.key_index, self._axis,
            mesh_cache_key(self.mesh),
        )
        self._prog = PROGRAM_CACHE.get_or_build(
            cache_key,
            lambda: _TracedProgram(
                lambda nargs: self._compile(nargs - 1)
            ),
        )
        self._result = None
        self._lock = obs_contention.TimedLock("mesh_sort")

    @property
    def schema(self):
        return self._schema

    @property
    def partition_count(self) -> int:
        return 1

    def describe(self) -> str:
        return (f"MeshSortExec[key={self.key_index}, "
                f"{int(self.mesh.shape[self._axis])} devices]")

    def _trace_key(self, sig) -> tuple:
        return ("mesh.sort", self.key_index,
                tuple(repr(f.dtype) for f in self._schema.fields), sig)

    def _compile(self, ncols: int):
        mesh, axis = self.mesh, self._axis
        ki = self.key_index

        def per_shard(num_rows_s, *cols_s):
            cols = [c[0] for c in cols_s]
            nr = num_rows_s[0]
            cap = cols[0].shape[0]
            dead = (jnp.arange(cap, dtype=jnp.int32) >= nr)
            # stable: primary = liveness (dead rows sink), secondary =
            # the key; ties keep input order within the shard
            order = jnp.lexsort((cols[ki], dead))
            return tuple(
                jnp.take(c, order)[None] for c in cols
            )

        fn = shard_map(
            per_shard, mesh=mesh,
            in_specs=(P(axis),) + tuple(P(axis) for _ in range(ncols)),
            out_specs=tuple([P(axis)] * ncols),
        )
        return jax.jit(fn)

    @staticmethod
    def _merge_runs(runs, key_index):
        """Stable pairwise merge of per-shard sorted runs (earlier
        shards win ties), vectorized with searchsorted."""
        merged = None
        for cols in runs:
            if merged is None:
                merged = [np.asarray(c) for c in cols]
                continue
            a_keys = merged[key_index]
            b_keys = np.asarray(cols[key_index])
            na, nb = len(a_keys), len(b_keys)
            pos_a = np.arange(na) + np.searchsorted(
                b_keys, a_keys, side="left"
            )
            pos_b = np.arange(nb) + np.searchsorted(
                a_keys, b_keys, side="right"
            )
            out = []
            for ac, bc in zip(merged, cols):
                bc = np.asarray(bc)
                m = np.empty(na + nb, dtype=ac.dtype)
                m[pos_a] = ac
                m[pos_b] = bc
                out.append(m)
            merged = out
        return merged

    def _run(self, ctx: ExecContext):
        with self._lock:
            if self._result is not None:
                return self._result
            source = self.children[0]
            n_dev = int(self.mesh.shape[self._axis])
            st = meshprof.stage(
                "mesh.sort", n_dev,
                lower_window=getattr(self, "_mesh_lower", None),
            )
            with st.phase("mesh_stage_in"):
                stacked, num_rows, cap, total, host_cols = (
                    stack_partitions(
                        source, ctx, self.mesh, self._axis
                    )
                )
                st.add_bytes(sum(h.nbytes for h in host_cols))
            with st.phase("mesh_trace"):
                if self._prog.prepare(num_rows, *stacked):
                    meshprof.note_trace(
                        "mesh.sort",
                        self._trace_key(meshprof.arg_signature(
                            num_rows, *stacked
                        )),
                    )
            t0 = time.monotonic()
            with st.phase("mesh_launch"):
                mesh_chaos("mesh.sort", n_dev, ctx)
                dispatch.record("dispatches")
                dispatch.record("mesh_dispatches")
                outs = self._prog(num_rows, *stacked)
            with st.phase("mesh_sync"):
                outs = jax.block_until_ready(outs)
            with st.phase("mesh_gather"):
                outs = dispatch.device_get(outs)
                nr_host = np.asarray(num_rows)
                runs = [
                    [np.asarray(c)[d][: int(nr_host[d])]
                     for c in outs]
                    for d in range(n_dev)
                    if int(nr_host[d]) > 0
                ]
                merged = (
                    self._merge_runs(runs, self.key_index)
                    if runs else None
                )
            t1 = st.finish()
            record_mesh_run(
                ctx, "mesh.sort", n_dev, t0, t1,
                [{"rows_in": int(nr_host[d]),
                  "rows_out": int(nr_host[d])}
                 for d in range(n_dev)],
                stage=st,
            )
            ctx.metrics.add("mesh.sort_rows", total)
            self._result = (merged,)
            return self._result

    def execute(self, partition: int, ctx: ExecContext
                ) -> Iterator[ColumnBatch]:
        if self.fallback is not None and not self._use_fallback:
            try:
                self._run(ctx)
            except Exception as e:  # noqa: BLE001 - ladder below
                degrade_or_raise(self, ctx, e)
        if self._use_fallback:
            if partition < self.fallback.partition_count:
                yield from self.fallback.execute(partition, ctx)
            return
        (merged,) = self._run(ctx)
        if merged is None:
            return
        n = len(merged[0])
        if self.fetch is not None:
            n = min(n, int(self.fetch))
        if n == 0:
            return
        cols: List[Column] = []
        for arr, f in zip(merged, self._schema.fields):
            cols.append(Column(
                f.dtype, arr[:n].astype(f.dtype.physical_dtype()),
                None, None,
            ))
        yield ColumnBatch(self._schema, cols, n)


# ---------------------------------------------------------------------------
# MeshRepartitionExec: hash repartition over ICI all_to_all (ISSUE 20)
# ---------------------------------------------------------------------------


class MeshRepartitionExec(PhysicalOp):
    """The hash ShuffleExchange as one mesh program: every input
    partition lands on a device, rows move to their key-hash owner with
    one `lax.all_to_all` per column (parallel/sharded.
    DistributedRepartition), and the mesh boundary yields one output
    partition per device - key-disjoint, exactly the contract a
    WindowExec's PARTITION BY needs. Schema passes through unchanged.
    """

    def __init__(self, child: PhysicalOp, keys, mesh=None,
                 fallback: Optional[PhysicalOp] = None):
        self.fallback = fallback
        self._use_fallback = False
        self.children = [child]
        self.mesh = mesh or get_mesh()
        self._axis = "data"
        self._schema = child.schema
        self.keys = list(keys)
        from blaze_tpu.fleet.program_cache import (
            PROGRAM_CACHE, mesh_cache_key,
        )
        from blaze_tpu.parallel.sharded import DistributedRepartition

        cache_key = (
            "mesh.repartition",
            tuple((f.name, repr(f.dtype), f.nullable)
                  for f in self._schema.fields),
            tuple(repr(k) for k in self.keys),
            self._axis,
            mesh_cache_key(self.mesh),
        )
        self._rp = PROGRAM_CACHE.get_or_build(
            cache_key,
            lambda: DistributedRepartition(
                self.mesh, self._schema, self.keys, axis=self._axis
            ),
        )
        self._result = None
        self._lock = obs_contention.TimedLock("mesh_repartition")

    @property
    def schema(self):
        return self._schema

    @property
    def partition_count(self) -> int:
        return int(self.mesh.shape[self._axis])

    def describe(self) -> str:
        return (f"MeshRepartitionExec[{len(self.keys)} keys, "
                f"{self.partition_count} devices]")

    def _trace_key(self, sig) -> tuple:
        return ("mesh.repartition",
                tuple(repr(k) for k in self._rp.keys), sig)

    def _run(self, ctx: ExecContext):
        with self._lock:
            if self._result is not None:
                return self._result
            child = self.children[0]
            n_dev = self.partition_count
            st = meshprof.stage(
                "mesh.repartition", n_dev,
                lower_window=getattr(self, "_mesh_lower", None),
            )
            with st.phase("mesh_stage_in"):
                stacked, num_rows, cap, total, host_cols = (
                    stack_partitions(
                        child, ctx, self.mesh, self._axis
                    )
                )
                st.add_bytes(sum(h.nbytes for h in host_cols))
            with st.phase("mesh_trace"):
                if self._rp.prepare(stacked, num_rows):
                    meshprof.note_trace(
                        "mesh.repartition",
                        self._trace_key(meshprof.arg_signature(
                            *stacked, num_rows
                        )),
                    )
            t0 = time.monotonic()
            with st.phase("mesh_launch"):
                mesh_chaos("mesh.repartition", n_dev, ctx)
                dispatch.record("dispatches")
                dispatch.record("mesh_dispatches")
                out_cols, live = self._rp(stacked, num_rows)
            with st.phase("mesh_sync"):
                out_cols, live = jax.block_until_ready(
                    (out_cols, live)
                )
            with st.phase("mesh_gather"):
                out_cols, live = dispatch.device_get((out_cols, live))
            t1 = st.finish()
            live = np.asarray(live)
            nbytes = total * sum(
                np.dtype(f.dtype.physical_dtype()).itemsize
                for f in self._schema.fields
            )
            record_exchange(ctx, "all_to_all", total, nbytes)
            nr_host = np.asarray(num_rows)
            record_mesh_run(
                ctx, "mesh.repartition", n_dev, t0, t1,
                [{"rows_in": int(nr_host[d]),
                  "rows_out": int(live[d].sum())}
                 for d in range(n_dev)],
                stage=st,
            )
            ctx.metrics.add("mesh.repartition_rows", total)
            self._result = (
                [np.asarray(c) for c in out_cols], live
            )
            return self._result

    def execute(self, partition: int, ctx: ExecContext
                ) -> Iterator[ColumnBatch]:
        if self.fallback is not None and not self._use_fallback:
            try:
                self._run(ctx)
            except Exception as e:  # noqa: BLE001 - ladder below
                degrade_or_raise(self, ctx, e)
        if self._use_fallback:
            if partition < self.fallback.partition_count:
                yield from self.fallback.execute(partition, ctx)
            return
        out_cols, live = self._run(ctx)
        idx = np.nonzero(live[partition])[0]
        if len(idx) == 0:
            return
        cols: List[Column] = []
        for arr, f in zip(out_cols, self._schema.fields):
            cols.append(Column(
                f.dtype,
                arr[partition][idx].astype(f.dtype.physical_dtype()),
                None, None,
            ))
        yield ColumnBatch(self._schema, cols, len(idx))
