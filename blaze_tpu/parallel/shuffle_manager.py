"""Pluggable shuffle manager: the embedder-facing shuffle surface.

Reference counterpart: `ArrowShuffleManager301` (shuffle/
ArrowShuffleManager301.scala:39) - the component a HOST system (Spark's
`spark.shuffle.manager` slot) drives to register shuffles, obtain
writers for map tasks, commit their output atomically, and hand reduce
tasks readers. The engine's own ShuffleExchangeExec orchestrates its
shuffles internally (as Spark's exchange does through the manager); this
class exposes the same lifecycle to embedders - the gateway, the C-ABI
embedding, or a future Spark session-extension tier - over the shared
`.data`/`.index` segmented-IPC format, accepting BOTH producers (native
ShuffleWriterExec plans and host-tier pyarrow batches, mirroring the
reference's native + JVM-row writer pair).

Lifecycle (all paths are manager-owned):
  h = manager.register_shuffle(num_maps, num_partitions, keys=...)
  manager.write_map_native(h, map_id, plan)        # device tier
  manager.write_map_batches(h, map_id, batches)    # host tier
  manager.read_partition(h, p [, map_range])       # -> RecordBatches
  manager.map_statistics(h)                        # AQE stats feed
  manager.remove_shuffle(h)                        # delete files
Commits are atomic (tmp files + rename, index last - the reference's
writeIndexFileAndCommit contract) and idempotent: re-committing a map id
replaces its output, which is what Spark's task retry requires.
"""

from __future__ import annotations

import dataclasses
import os
import shutil
import tempfile
import threading
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import pyarrow as pa

from blaze_tpu.io.ipc import partition_ranges, read_file_segment


@dataclasses.dataclass(frozen=True)
class ShuffleHandle:
    shuffle_id: int
    num_maps: int
    num_partitions: int
    key_names: Tuple[str, ...]
    root: str


class ShuffleManager:
    def __init__(self, root: Optional[str] = None):
        self.root = root or tempfile.mkdtemp(prefix="blz-shufmgr-")
        self._next_id = 0
        self._handles: Dict[int, ShuffleHandle] = {}
        self._committed: Dict[Tuple[int, int], Tuple[str, str]] = {}
        self._lock = threading.Lock()

    # -- lifecycle -----------------------------------------------------
    def register_shuffle(self, num_maps: int, num_partitions: int,
                         keys: Sequence[str]) -> ShuffleHandle:
        with self._lock:
            sid = self._next_id
            self._next_id += 1
            d = os.path.join(self.root, f"shuffle_{sid}")
            os.makedirs(d, exist_ok=True)
            h = ShuffleHandle(sid, num_maps, num_partitions,
                              tuple(keys), d)
            self._handles[sid] = h
            return h

    def remove_shuffle(self, h: ShuffleHandle) -> None:
        with self._lock:
            self._handles.pop(h.shuffle_id, None)
            for key in [k for k in self._committed
                        if k[0] == h.shuffle_id]:
                self._committed.pop(key, None)
        shutil.rmtree(h.root, ignore_errors=True)

    # -- write side ----------------------------------------------------
    def _paths(self, h: ShuffleHandle, map_id: int) -> Tuple[str, str]:
        return (os.path.join(h.root, f"map_{map_id}.data"),
                os.path.join(h.root, f"map_{map_id}.index"))

    def _commit(self, h: ShuffleHandle, map_id: int,
                tmp_data: str, tmp_index: str) -> List[int]:
        """Atomic, idempotent commit: data lands first, the index rename
        is the commit point (a reader never sees an index whose data is
        missing - the reference's writeIndexFileAndCommit ordering)."""
        data, index = self._paths(h, map_id)
        os.replace(tmp_data, data)
        os.replace(tmp_index, index)
        with self._lock:
            self._committed[(h.shuffle_id, map_id)] = (data, index)
        return [length for _, length in partition_ranges(index)]

    def write_map_native(self, h: ShuffleHandle, map_id: int,
                         child, ctx=None) -> List[int]:
        """Run a native ShuffleWriterExec over `child`'s partition
        `map_id` (the device hash tier). Returns partition lengths."""
        from blaze_tpu.exprs import ir
        from blaze_tpu.ops.base import ExecContext
        from blaze_tpu.ops.shuffle_writer import ShuffleWriterExec

        tmp_data, tmp_index = (
            p + f".tmp{os.getpid()}" for p in self._paths(h, map_id)
        )
        writer = ShuffleWriterExec(
            child, [ir.Col(k) for k in h.key_names],
            h.num_partitions, tmp_data, tmp_index,
        )
        for _ in writer.execute(map_id, ctx or ExecContext()):
            pass
        return self._commit(h, map_id, tmp_data, tmp_index)

    def write_map_batches(self, h: ShuffleHandle, map_id: int,
                          batches: Iterator[pa.RecordBatch]
                          ) -> List[int]:
        """Write host rows (the JVM-row-writer analog): same format,
        no device involvement."""
        from blaze_tpu.ops.host_shuffle import host_shuffle_write

        tmp_data, tmp_index = (
            p + f".tmp{os.getpid()}" for p in self._paths(h, map_id)
        )
        host_shuffle_write(
            batches, list(h.key_names), h.num_partitions,
            tmp_data, tmp_index, spill_dir=h.root,
        )
        return self._commit(h, map_id, tmp_data, tmp_index)

    # -- read side -----------------------------------------------------
    def read_partition(self, h: ShuffleHandle, partition: int,
                       map_range: Optional[Tuple[int, int]] = None
                       ) -> Iterator[pa.RecordBatch]:
        """Stream one reduce partition across the selected map outputs
        (map_range enables AQE partial-mapper reads,
        NativeSupports.scala:131-212)."""
        lo, hi = map_range or (0, h.num_maps)
        for m in range(lo, hi):
            with self._lock:
                paths = self._committed.get((h.shuffle_id, m))
            if paths is None:
                raise KeyError(
                    f"map {m} of shuffle {h.shuffle_id} not committed"
                )
            data, index = paths
            off, length = partition_ranges(index)[partition]
            if length:
                yield from read_file_segment(data, off, length)

    def map_statistics(self, h: ShuffleHandle) -> List[int]:
        """Bytes per reduce partition summed over committed maps - the
        AQE stats feed (mapOutputStatisticsFuture analog)."""
        sizes = [0] * h.num_partitions
        for m in range(h.num_maps):
            with self._lock:
                paths = self._committed.get((h.shuffle_id, m))
            if paths is None:
                continue
            for p, (_, length) in enumerate(
                partition_ranges(paths[1])
            ):
                sizes[p] += length
        return sizes
