"""Device mesh helpers.

The engine scales with one logical axis today - 'data', carrying query
partitions (the reference's task-per-partition model, NativeRDD.scala:41) -
and keeps the mesh-creation surface general so wider topologies (e.g. a
second axis for intra-operator sharding of giant builds) slot in without
touching operators."""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec


def device_count() -> int:
    return len(jax.devices())


def get_mesh(shape: Optional[Tuple[int, ...]] = None,
             axis_names: Sequence[str] = ("data",)) -> Mesh:
    devs = jax.devices()
    if shape is None:
        shape = (len(devs),)
    n = int(np.prod(shape))
    if n > len(devs):
        raise ValueError(
            f"mesh shape {shape} needs {n} devices, have {len(devs)}"
        )
    arr = np.array(devs[:n]).reshape(shape)
    return Mesh(arr, tuple(axis_names))


def data_sharding(mesh: Mesh) -> NamedSharding:
    """Shard leading (partition) axis across the 'data' mesh axis."""
    return NamedSharding(mesh, PartitionSpec("data"))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def allgather_rows(x, n_dev: int, trailing: bool = True):
    """Gather a data-sharded array onto every process, normalized to
    [n_dev, ...] regardless of how allgather stacks the shards. The
    shared normalization for the launcher workloads and the mesh
    operators (divergent private copies drift)."""
    from jax.experimental import multihost_utils

    g = np.asarray(multihost_utils.process_allgather(x, tiled=True))
    return g.reshape((n_dev, -1) if trailing else (n_dev,))
