"""Arrow-aligned logical type system.

Covers the reference's supported type surface (Spark<->Arrow map,
spark-extension NativeConverters.scala:117-213 and plan-serde arrow type
messages plan.proto): null, bool, int8/16/32/64, float32/64, utf8, binary,
date32, timestamp (microseconds), decimal(precision, scale).

Device representation (TPU-first, ragged-free):
- fixed-width types map 1:1 to a device array of the physical dtype
- utf8/binary are dictionary-encoded: an int32 code array on device plus a
  host-side dictionary (the reference instead streams raw Arrow string
  buffers; TPUs have no string compute, so we normalize early - SURVEY 7)
- date32 is int32 days, timestamp is int64 microseconds
- decimal(p, s) is an int64 unscaled value (the reference constrains decimals
  to i64 the same way: plan.proto:598-601 "only use i64 for blaze")
- validity is a separate bool device array (None == all valid)
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional, Sequence, Tuple

import numpy as np


class TypeId(enum.Enum):
    NULL = "null"
    BOOL = "bool"
    INT8 = "int8"
    INT16 = "int16"
    INT32 = "int32"
    INT64 = "int64"
    FLOAT32 = "float32"
    FLOAT64 = "float64"
    UTF8 = "utf8"
    BINARY = "binary"
    DATE32 = "date32"
    TIMESTAMP_US = "timestamp_us"
    DECIMAL = "decimal"


@dataclasses.dataclass(frozen=True)
class DataType:
    id: TypeId
    # Only meaningful for DECIMAL.
    precision: int = 0
    scale: int = 0

    # ---- constructors ----
    @staticmethod
    def null() -> "DataType":
        return DataType(TypeId.NULL)

    @staticmethod
    def bool_() -> "DataType":
        return DataType(TypeId.BOOL)

    @staticmethod
    def int8() -> "DataType":
        return DataType(TypeId.INT8)

    @staticmethod
    def int16() -> "DataType":
        return DataType(TypeId.INT16)

    @staticmethod
    def int32() -> "DataType":
        return DataType(TypeId.INT32)

    @staticmethod
    def int64() -> "DataType":
        return DataType(TypeId.INT64)

    @staticmethod
    def float32() -> "DataType":
        return DataType(TypeId.FLOAT32)

    @staticmethod
    def float64() -> "DataType":
        return DataType(TypeId.FLOAT64)

    @staticmethod
    def utf8() -> "DataType":
        return DataType(TypeId.UTF8)

    @staticmethod
    def binary() -> "DataType":
        return DataType(TypeId.BINARY)

    @staticmethod
    def date32() -> "DataType":
        return DataType(TypeId.DATE32)

    @staticmethod
    def timestamp_us() -> "DataType":
        return DataType(TypeId.TIMESTAMP_US)

    @staticmethod
    def decimal(precision: int, scale: int) -> "DataType":
        return DataType(TypeId.DECIMAL, precision, scale)

    # ---- classification ----
    @property
    def is_numeric(self) -> bool:
        return self.id in _NUMERIC

    @property
    def is_integer(self) -> bool:
        return self.id in _INTEGER

    @property
    def is_floating(self) -> bool:
        return self.id in (TypeId.FLOAT32, TypeId.FLOAT64)

    @property
    def is_string_like(self) -> bool:
        return self.id in (TypeId.UTF8, TypeId.BINARY)

    @property
    def is_dictionary_encoded(self) -> bool:
        """True when the device representation is int32 codes + host dict."""
        return self.is_string_like

    @property
    def is_wide_decimal(self) -> bool:
        """DECIMAL whose unscaled values exceed i64 (precision > 18):
        the device representation is a (capacity, 2) int64 array of
        little-endian limbs [lo64-bit-pattern, hi64] (the reference's
        16-byte decimal shuffle slot, shuffle_writer_exec.rs:196-220).
        Wide columns pass through scans/aggregates exactly; value
        compute on them is host-tier work."""
        return self.id is TypeId.DECIMAL and self.precision > 18

    def physical_dtype(self) -> np.dtype:
        """numpy dtype of the on-device value array."""
        return np.dtype(_PHYSICAL[self.id])

    def __repr__(self) -> str:
        if self.id is TypeId.DECIMAL:
            return f"decimal({self.precision},{self.scale})"
        return self.id.value


_NUMERIC = {
    TypeId.INT8,
    TypeId.INT16,
    TypeId.INT32,
    TypeId.INT64,
    TypeId.FLOAT32,
    TypeId.FLOAT64,
    TypeId.DECIMAL,
}
_INTEGER = {TypeId.INT8, TypeId.INT16, TypeId.INT32, TypeId.INT64}

_PHYSICAL = {
    TypeId.NULL: np.int8,
    TypeId.BOOL: np.bool_,
    TypeId.INT8: np.int8,
    TypeId.INT16: np.int16,
    TypeId.INT32: np.int32,
    TypeId.INT64: np.int64,
    TypeId.FLOAT32: np.float32,
    TypeId.FLOAT64: np.float64,
    TypeId.UTF8: np.int32,  # dictionary codes
    TypeId.BINARY: np.int32,  # dictionary codes
    TypeId.DATE32: np.int32,
    TypeId.TIMESTAMP_US: np.int64,
    TypeId.DECIMAL: np.int64,  # unscaled value
}


@dataclasses.dataclass(frozen=True)
class Field:
    name: str
    dtype: DataType
    nullable: bool = True

    def with_name(self, name: str) -> "Field":
        return Field(name, self.dtype, self.nullable)


@dataclasses.dataclass(frozen=True)
class Schema:
    fields: Tuple[Field, ...]

    def __init__(self, fields: Sequence[Field]):
        object.__setattr__(self, "fields", tuple(fields))

    def __len__(self) -> int:
        return len(self.fields)

    def __iter__(self):
        return iter(self.fields)

    def names(self) -> Tuple[str, ...]:
        return tuple(f.name for f in self.fields)

    def index_of(self, name: str) -> int:
        for i, f in enumerate(self.fields):
            if f.name == name:
                return i
        raise KeyError(f"no field named {name!r} in {self.names()}")

    def field(self, name_or_index) -> Field:
        if isinstance(name_or_index, int):
            return self.fields[name_or_index]
        return self.fields[self.index_of(name_or_index)]

    def rename(self, names: Sequence[str]) -> "Schema":
        """Positional rename (reference RenameColumnsExec semantics,
        rename_columns_exec.rs:38-75)."""
        if len(names) != len(self.fields):
            raise ValueError("rename arity mismatch")
        return Schema([f.with_name(n) for f, n in zip(self.fields, names)])

    def select(self, indices: Sequence[int]) -> "Schema":
        return Schema([self.fields[i] for i in indices])


# ---------------------------------------------------------------------------
# pyarrow interop (host boundary only; never imported inside jitted code)
# ---------------------------------------------------------------------------

def to_arrow_type(dt: DataType):
    import pyarrow as pa

    m = {
        TypeId.NULL: pa.null(),
        TypeId.BOOL: pa.bool_(),
        TypeId.INT8: pa.int8(),
        TypeId.INT16: pa.int16(),
        TypeId.INT32: pa.int32(),
        TypeId.INT64: pa.int64(),
        TypeId.FLOAT32: pa.float32(),
        TypeId.FLOAT64: pa.float64(),
        TypeId.UTF8: pa.utf8(),
        TypeId.BINARY: pa.binary(),
        TypeId.DATE32: pa.date32(),
        TypeId.TIMESTAMP_US: pa.timestamp("us"),
    }
    if dt.id is TypeId.DECIMAL:
        return __import__("pyarrow").decimal128(dt.precision, dt.scale)
    return m[dt.id]


def from_arrow_type(at) -> DataType:
    import pyarrow as pa
    import pyarrow.types as pat

    if pat.is_dictionary(at):
        return from_arrow_type(at.value_type)
    if pat.is_null(at):
        return DataType.null()
    if pat.is_boolean(at):
        return DataType.bool_()
    if pat.is_int8(at):
        return DataType.int8()
    if pat.is_int16(at):
        return DataType.int16()
    if pat.is_int32(at):
        return DataType.int32()
    if pat.is_int64(at):
        return DataType.int64()
    if pat.is_float32(at):
        return DataType.float32()
    if pat.is_float64(at):
        return DataType.float64()
    if pat.is_string(at) or pat.is_large_string(at):
        return DataType.utf8()
    if pat.is_binary(at) or pat.is_large_binary(at):
        return DataType.binary()
    if pat.is_date32(at):
        return DataType.date32()
    if pat.is_timestamp(at):
        return DataType.timestamp_us()
    if pat.is_decimal(at):
        return DataType.decimal(at.precision, at.scale)
    raise NotImplementedError(f"unsupported arrow type {at}")


def to_arrow_schema(schema: Schema):
    import pyarrow as pa

    return pa.schema(
        [pa.field(f.name, to_arrow_type(f.dtype), f.nullable) for f in schema]
    )


def from_arrow_schema(aschema) -> Schema:
    return Schema(
        [Field(f.name, from_arrow_type(f.type), f.nullable) for f in aschema]
    )
