"""Double-buffered host pipeline.

The reference's hot loop is a tokio stream pumping batches through a
rendezvous queue (exec.rs:196-255); the TPU-first equivalent (SURVEY 7
"streaming model") overlaps host-side work (parquet decode, IPC decode,
dictionary encoding, H2D issue) with device compute by running the
producer iterator on a worker thread ahead of the consumer, bounded by a
small queue. JAX dispatch is async already, so two stages of lookahead
keep both the host decoder and the device busy.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterator, TypeVar

T = TypeVar("T")

_SENTINEL = object()


def prefetch(it: Iterator[T], depth: int = 2) -> Iterator[T]:
    """Run `it` on a background thread with `depth` items of lookahead.
    Exceptions propagate to the consumer at the point of consumption;
    early consumer exit stops the producer."""
    q: "queue.Queue" = queue.Queue(maxsize=max(1, depth))
    stop = threading.Event()

    def worker():
        try:
            for item in it:
                if stop.is_set():
                    return
                q.put(item)
            q.put(_SENTINEL)
        except BaseException as e:  # noqa: BLE001 - forwarded to consumer
            q.put(e)

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is _SENTINEL:
                return
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        stop.set()
        # drain so a blocked producer can observe the stop flag
        try:
            while True:
                q.get_nowait()
        except queue.Empty:
            pass


class PrefetchExec:
    """Operator wrapper adding producer-side lookahead to any child."""

    def __init__(self, child, depth: int = 2):
        self.children = [child]
        self.depth = depth

    @property
    def schema(self):
        return self.children[0].schema

    @property
    def partition_count(self):
        return self.children[0].partition_count

    def describe(self):
        return f"PrefetchExec(depth={self.depth})"

    def display(self, indent: int = 0) -> str:
        lines = ["  " * indent + self.describe()]
        for c in self.children:
            lines.append(c.display(indent + 1))
        return "\n".join(lines)

    def fingerprint(self):
        return self.children[0].fingerprint()

    def execute(self, partition: int, ctx):
        return prefetch(
            self.children[0].execute(partition, ctx), self.depth
        )
