"""ctypes bindings to the C++ host runtime (cpp/blaze_host.cpp).

The shared library builds lazily on first use (g++ -O3 -march=native,
linked against the system libzstd) and is cached next to the source with a
content hash, so a source change rebuilds automatically. Falls back to pure
Python (zstandard module + numpy murmur3) if the toolchain is unavailable -
the engine stays functional, just slower on host-side byte crunching.
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
import tempfile
from typing import Optional

import numpy as np

log = logging.getLogger("blaze_tpu.native")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_CPP_SRC = os.path.join(_REPO_ROOT, "cpp", "blaze_host.cpp")

_lib: Optional[ctypes.CDLL] = None
_lib_tried = False


def _build_lib() -> Optional[str]:
    with open(_CPP_SRC, "rb") as f:
        src = f.read()
    tag = hashlib.sha256(src).hexdigest()[:16]
    cache_dir = os.path.join(
        tempfile.gettempdir(), "blaze_tpu_native"
    )
    os.makedirs(cache_dir, exist_ok=True)
    so_path = os.path.join(cache_dir, f"libblaze_host_{tag}.so")
    if os.path.exists(so_path):
        return so_path
    cmd = [
        "g++", "-O3", "-march=native", "-shared", "-fPIC", "-std=c++17",
        _CPP_SRC, "-o", so_path + ".tmp", "-lzstd",
    ]
    try:
        subprocess.run(
            cmd, check=True, capture_output=True, timeout=120
        )
        os.replace(so_path + ".tmp", so_path)
        return so_path
    except Exception as e:  # toolchain missing / compile error
        log.warning("native host lib build failed, using Python fallback: %s",
                    e)
        return None


def get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _lib_tried
    if _lib is not None or _lib_tried:
        return _lib
    _lib_tried = True
    if os.environ.get("BLAZE_DISABLE_NATIVE"):
        return None
    path = _build_lib()
    if path is None:
        return None
    lib = ctypes.CDLL(path)
    c = ctypes
    i64, i32, u8p, i64p, i32p, u32p = (
        c.c_int64, c.c_int32, c.POINTER(c.c_uint8), c.POINTER(c.c_int64),
        c.POINTER(c.c_int32), c.POINTER(c.c_uint32),
    )
    lib.blz_zstd_compress_bound.restype = i64
    lib.blz_zstd_compress_bound.argtypes = [i64]
    lib.blz_zstd_compress.restype = i64
    lib.blz_zstd_compress.argtypes = [u8p, i64, u8p, i64, c.c_int]
    lib.blz_zstd_decompress.restype = i64
    lib.blz_zstd_decompress.argtypes = [u8p, i64, u8p, i64]
    lib.blz_zstd_frame_content_size.restype = i64
    lib.blz_zstd_frame_content_size.argtypes = [u8p, i64]
    lib.blz_zstd_decompress_stream.restype = i64
    lib.blz_zstd_decompress_stream.argtypes = [u8p, i64, u8p, i64]
    lib.blz_murmur3_strings_chain.restype = None
    lib.blz_murmur3_strings_chain.argtypes = [u8p, i32p, u8p, i64, u32p]
    lib.blz_murmur3_dict_strings_chain.restype = None
    lib.blz_murmur3_dict_strings_chain.argtypes = [
        u8p, i32p, i32p, u8p, i64, u32p
    ]
    lib.blz_murmur3_i32_chain.restype = None
    lib.blz_murmur3_i32_chain.argtypes = [i32p, u8p, i64, u32p]
    lib.blz_murmur3_i64_chain.restype = None
    lib.blz_murmur3_i64_chain.argtypes = [i64p, u8p, i64, u32p]
    lib.blz_pmod.restype = None
    lib.blz_pmod.argtypes = [u32p, i64, i32, i32p]
    lib.blz_shuffle_assemble.restype = i64
    lib.blz_shuffle_assemble.argtypes = [
        c.c_char_p, c.c_char_p, u8p, i64p, i32,
        c.POINTER(c.c_char_p), i32, i64p,
    ]
    _lib = lib
    return _lib


def _as(ptr_type, arr: np.ndarray):
    return arr.ctypes.data_as(ptr_type)


# ---------------------------------------------------------------------------
# zstd with Python fallback
# ---------------------------------------------------------------------------

# Last-resort frame marker when NO zstd implementation exists in the
# environment (neither the C++ host lib nor the `zstandard` module -
# toolchain-less containers). Frames start with these 8 bytes followed by
# the raw payload; a real zstd frame starts with magic 28 B5 2F FD, so
# the two can never be confused. Wire bit-compat with the reference is
# only claimed when a zstd tier exists - this keeps the shuffle/cluster
# machinery functional (self-consistent) instead of crashing.
_RAW_FRAME_MAGIC = b"BLZRAW\x00\x01"


def _py_zstd():
    try:
        import zstandard

        return zstandard
    except ImportError:
        return None


def zstd_compress(data: bytes, level: int = 1) -> bytes:
    lib = get_lib()
    if lib is None:
        zstandard = _py_zstd()
        if zstandard is None:
            return _RAW_FRAME_MAGIC + data

        return zstandard.ZstdCompressor(level=level).compress(data)
    src = np.frombuffer(data, dtype=np.uint8)
    bound = lib.blz_zstd_compress_bound(len(data))
    dst = np.empty(bound, dtype=np.uint8)
    n = lib.blz_zstd_compress(
        _as(ctypes.POINTER(ctypes.c_uint8), src), len(data),
        _as(ctypes.POINTER(ctypes.c_uint8), dst), bound, level,
    )
    if n < 0:
        raise IOError("zstd compression failed")
    return dst[:n].tobytes()


def zstd_decompress(data: bytes, hint: Optional[int] = None) -> bytes:
    if data[:8] == _RAW_FRAME_MAGIC:
        # raw fallback frame (zstd-less writer); readable regardless of
        # which zstd tier THIS process has
        return data[8:]
    lib = get_lib()
    if lib is None:
        zstandard = _py_zstd()
        if zstandard is None:
            raise IOError(
                "zstd frame received but no zstd implementation is "
                "available (install zstandard or the C++ host lib)"
            )

        return zstandard.ZstdDecompressor().decompressobj().decompress(data)
    src = np.frombuffer(data, dtype=np.uint8)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    size = lib.blz_zstd_frame_content_size(_as(u8p, src), len(data))
    if size >= 0:
        dst = np.empty(size, dtype=np.uint8)
        n = lib.blz_zstd_decompress(
            _as(u8p, src), len(data), _as(u8p, dst), size
        )
        if n < 0:
            raise IOError("zstd decompression failed")
        return dst[:n].tobytes()
    # unknown content size (streaming frames): grow-and-retry
    cap = hint or max(len(data) * 8, 1 << 20)
    while True:
        dst = np.empty(cap, dtype=np.uint8)
        n = lib.blz_zstd_decompress_stream(
            _as(u8p, src), len(data), _as(u8p, dst), cap
        )
        if n == -3:
            cap *= 4
            continue
        if n < 0:
            raise IOError("zstd stream decompression failed")
        return dst[:n].tobytes()


# ---------------------------------------------------------------------------
# murmur3 chains with Python fallback
# ---------------------------------------------------------------------------

def murmur3_strings_chain(arr, hashes: np.ndarray) -> np.ndarray:
    """Chain a pyarrow StringArray into running per-row hashes (uint32,
    modified in place and returned). NULL rows keep their seed."""
    import pyarrow as pa

    lib = get_lib()
    n = len(arr)
    if lib is None:
        from blaze_tpu.exprs.hashing import hash_bytes_host

        vals = arr.to_pylist()
        for i, s in enumerate(vals):
            if s is None:
                continue
            b = s.encode("utf-8") if isinstance(s, str) else s
            hashes[i] = np.uint32(hash_bytes_host(b, int(hashes[i])))
        return hashes
    arr = arr.combine_chunks() if isinstance(arr, pa.ChunkedArray) else arr
    if pa.types.is_dictionary(arr.type):
        arr = arr.dictionary_decode()
    if pa.types.is_large_string(arr.type):
        # the C walk reads int32 offsets; large_string carries int64
        arr = arr.cast(pa.string())
    if arr.offset != 0:
        arr = pa.concat_arrays([arr])  # re-materialize at offset 0
    bufs = arr.buffers()
    validity_np = None
    if arr.null_count > 0:
        validity_np = np.asarray(arr.is_valid()).astype(np.uint8)
    offsets = np.frombuffer(bufs[1], dtype=np.int32)[: n + 1]
    data = (
        np.frombuffer(bufs[2], dtype=np.uint8)
        if bufs[2] is not None
        else np.zeros(1, dtype=np.uint8)
    )
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.blz_murmur3_strings_chain(
        _as(u8p, data),
        _as(ctypes.POINTER(ctypes.c_int32),
            np.ascontiguousarray(offsets)),
        _as(u8p, validity_np) if validity_np is not None else None,
        n,
        _as(ctypes.POINTER(ctypes.c_uint32), hashes),
    )
    return hashes


def murmur3_dict_strings_chain(dictionary, codes: np.ndarray,
                               validity: Optional[np.ndarray],
                               hashes: np.ndarray) -> np.ndarray:
    """Chain a dictionary-encoded string column into running per-row hashes
    (uint32, in place). `dictionary` is a pyarrow StringArray; codes int32."""
    import pyarrow as pa

    lib = get_lib()
    n = len(codes)
    if lib is None or len(dictionary) == 0:
        from blaze_tpu.exprs.hashing import hash_bytes_host

        vals = dictionary.to_pylist()
        for i in range(n):
            if validity is not None and not validity[i]:
                continue
            s = vals[int(codes[i])] if vals else ""
            b = s.encode("utf-8") if isinstance(s, str) else (s or b"")
            hashes[i] = np.uint32(hash_bytes_host(b, int(hashes[i])))
        return hashes
    d = dictionary
    if isinstance(d, pa.ChunkedArray):
        d = d.combine_chunks()
    d = d.cast(pa.utf8())
    if d.offset != 0:
        d = pa.concat_arrays([d])
    bufs = d.buffers()
    offsets = np.frombuffer(bufs[1], dtype=np.int32)[: len(d) + 1]
    data = (
        np.frombuffer(bufs[2], dtype=np.uint8)
        if bufs[2] is not None
        else np.zeros(1, dtype=np.uint8)
    )
    u8p = ctypes.POINTER(ctypes.c_uint8)
    validity_np = (
        np.ascontiguousarray(validity).astype(np.uint8)
        if validity is not None
        else None
    )
    lib.blz_murmur3_dict_strings_chain(
        _as(u8p, data),
        _as(ctypes.POINTER(ctypes.c_int32),
            np.ascontiguousarray(offsets)),
        _as(ctypes.POINTER(ctypes.c_int32),
            np.ascontiguousarray(codes.astype(np.int32))),
        _as(u8p, validity_np) if validity_np is not None else None,
        n,
        _as(ctypes.POINTER(ctypes.c_uint32), hashes),
    )
    return hashes


def pmod_np(hashes: np.ndarray, num_partitions: int) -> np.ndarray:
    lib = get_lib()
    n = len(hashes)
    out = np.empty(n, dtype=np.int32)
    if lib is None:
        h = hashes.view(np.int32)
        r = h % np.int32(num_partitions)
        return np.where(r < 0, r + num_partitions, r).astype(np.int32)
    lib.blz_pmod(
        _as(ctypes.POINTER(ctypes.c_uint32), hashes), n,
        num_partitions, _as(ctypes.POINTER(ctypes.c_int32), out),
    )
    return out


def shuffle_assemble(data_path: str, index_path: str,
                     partition_buffers, num_partitions: int,
                     spills=None) -> None:
    """Write the .data/.index pair from per-partition segment buffers plus
    spill files (reference shuffle_writer_exec.rs:437-506 format)."""
    spills = spills or []
    lib = get_lib()
    if lib is None:
        _shuffle_assemble_py(
            data_path, index_path, partition_buffers, num_partitions, spills
        )
        return
    blob = b"".join(partition_buffers)
    offs = np.zeros(num_partitions + 1, dtype=np.int64)
    pos = 0
    for i, b in enumerate(partition_buffers):
        offs[i] = pos
        pos += len(b)
    offs[num_partitions] = pos
    blob_np = (
        np.frombuffer(blob, dtype=np.uint8)
        if blob
        else np.zeros(1, dtype=np.uint8)
    )
    n_spills = len(spills)
    spill_paths = (ctypes.c_char_p * max(n_spills, 1))()
    spill_offs = np.zeros(
        (max(n_spills, 1), num_partitions + 1), dtype=np.int64
    )
    for i, (path, so) in enumerate(spills):
        spill_paths[i] = path.encode()
        spill_offs[i, :] = so
    rc = lib.blz_shuffle_assemble(
        data_path.encode(), index_path.encode(),
        _as(ctypes.POINTER(ctypes.c_uint8), blob_np),
        _as(ctypes.POINTER(ctypes.c_int64), offs),
        num_partitions, spill_paths, n_spills,
        _as(ctypes.POINTER(ctypes.c_int64),
            np.ascontiguousarray(spill_offs)),
    )
    if rc != 0:
        raise IOError(f"shuffle assemble failed: {rc}")


def _shuffle_assemble_py(data_path, index_path, partition_buffers,
                         num_partitions, spills):
    offsets = [0] * (num_partitions + 1)
    with open(data_path, "wb") as out:
        pos = 0
        for p in range(num_partitions):
            offsets[p] = pos
            buf = partition_buffers[p]
            out.write(buf)
            pos += len(buf)
            for path, so in spills:
                length = so[p + 1] - so[p]
                if length > 0:
                    with open(path, "rb") as f:
                        f.seek(so[p])
                        out.write(f.read(length))
                    pos += length
        offsets[num_partitions] = pos
    with open(index_path, "wb") as idx:
        for off in offsets:
            idx.write(int(off).to_bytes(8, "little"))
