"""Runtime: native host library bindings, memory management, task executor.

TPU-native counterparts of the reference's runtime tier: the JNI entry /
session bootstrap (exec.rs), the MemoryConsumer/spill protocol
(shuffle_writer_exec.rs:570-623), and metrics (metrics.rs).
"""
