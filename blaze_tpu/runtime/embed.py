"""In-process C-ABI embedding: execute_task behind Arrow C-Data.

The reference's defining boundary is an IN-PROCESS pointer handoff: the
native engine exports each finished batch as an Arrow C-Data
`ArrowSchema`/`ArrowArray` pair straight into its embedder's memory
(exec.rs:233-243 `export_array_into_raw`; consumer side
FFIHelper.scala:57-130 imports the pair). The socket gateway
(runtime/gateway.py) proves the same contract over TCP but copies every
byte; this module is the zero-copy tier: `cpp/blaze_embed.cpp` hosts
CPython in the embedder process, calls `open_stream`/`export_next`
here, and pyarrow's `_export_to_c` hands the embedder raw buffer
pointers plus a release callback - no IPC, no serialization, one
process.

Contract (mirrors BlazeCallNativeWrapper.nextBatch semantics,
NativeSupports.scala:285-301):
  open_stream(blob)              -> opaque stream object
  export_next(stream, s_ptr, a_ptr) -> 1 batch exported | 0 exhausted
  on error: raises - the C layer converts to blz_last_error().
"""

from __future__ import annotations

from typing import Iterator, Optional


class _Stream:
    __slots__ = ("it", "current")

    def __init__(self, it: Iterator):
        self.it = it
        # the previously exported batch is parked here so its buffers
        # outlive the consumer's copy window even if the consumer calls
        # export_next again before invoking the release callback
        self.current = None


def open_stream(blob: bytes) -> _Stream:
    """Decode a TaskDefinition and start executing it; batches stream
    out through export_next."""
    from blaze_tpu.runtime.executor import execute_task

    return _Stream(iter(execute_task(bytes(blob))))


def export_next(stream: _Stream, schema_ptr: int, array_ptr: int) -> int:
    """Export the next batch into caller-allocated ArrowSchema /
    ArrowArray structs (addresses as ints). Returns 1 if a batch was
    exported, 0 when the stream is exhausted."""
    rb = next(stream.it, None)
    if rb is None:
        stream.current = None
        return 0
    # ownership note: _export_to_c moves ownership of the buffers into
    # the C structs; pyarrow keeps them alive until the consumer calls
    # the embedded release callback, so `current` is belt-and-braces for
    # consumers that defer the release past the next call
    rb._export_to_c(array_ptr, schema_ptr)
    stream.current = rb
    return 1


def run_task_checksums(blob: bytes) -> list:
    """Debug/parity helper: execute the same blob in-process and return
    per-column float checksums (sum of valid values; dictionary columns
    sum their codes) - what cpp/blaze_embed_main.cpp prints, computed
    the pyarrow way. Tests compare the two."""
    import pyarrow as pa
    import pyarrow.compute as pc

    from blaze_tpu.runtime.executor import execute_task

    sums: Optional[list] = None
    rows = 0
    for rb in execute_task(blob):
        rows += rb.num_rows
        vals = []
        for col in rb.columns:
            if pa.types.is_dictionary(col.type):
                col = col.indices
            if pa.types.is_boolean(col.type):
                col = col.cast(pa.int8())
            vals.append(float(pc.sum(col).as_py() or 0.0))
        sums = vals if sums is None else [a + b
                                          for a, b in zip(sums, vals)]
    return [rows] + (sums or [])
