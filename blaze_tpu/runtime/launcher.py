"""Multi-process (multi-host) mesh launcher.

The reference scales across hosts on Spark's netty fabric; the TPU-native
equivalent for DEVICE-tier collectives is a jax.distributed process group:
every host runs one engine process, `jax.distributed.initialize` stitches
their local chips into one global mesh, and the engine's distributed
operators (parallel/sharded.py) run as a single SPMD program with XLA
collectives riding ICI within a host and DCN between hosts.

Two entry points:
- `initialize_worker(...)`: call FIRST in a worker process (before any
  backend init); joins the process group and returns the global Mesh.
- `launch_local(num_processes, ...)`: driver-side helper that spawns N
  local worker processes (each with its own virtual device pool on CPU,
  or its own TPU chips in production) running this module's smoke
  workload - the single-machine stand-in for one-process-per-host, used
  by tests and as the template for a real multi-host deployment.

The smoke workload runs DistributedGroupBy over the global mesh: every
process holds only its local shards; the result is allgathered and
checked against a numpy reference on every process (rank-symmetric, so
a pass means the cross-process collectives actually moved data).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
from typing import Optional


def initialize_worker(coordinator: str, num_processes: int,
                      process_id: int,
                      local_device_count: Optional[int] = None,
                      platform: Optional[str] = None):
    """Join the process group and return (jax module, global Mesh over
    the 'data' axis). Must run before any jax backend initialization."""
    if local_device_count is not None:
        # an explicit request overrides whatever the environment set
        # (e.g. a sitecustomize that rewrites XLA_FLAGS at startup)
        import re

        flags = os.environ.get("XLA_FLAGS", "")
        flags = re.sub(
            r"--xla_force_host_platform_device_count=\d+", "", flags
        )
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count="
            f"{local_device_count}"
        ).strip()
    import jax

    if platform:
        jax.config.update("jax_platforms", platform)
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    import numpy as np
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()), ("data",))
    return jax, mesh


def _worker_main(coordinator: str, num_processes: int, process_id: int,
                 local_device_count: int) -> int:
    jax, mesh = initialize_worker(
        coordinator, num_processes, process_id,
        local_device_count=local_device_count,
        platform=os.environ.get("BLAZE_LAUNCH_PLATFORM") or None,
    )
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import multihost_utils

    jax.config.update("jax_enable_x64", True)

    from blaze_tpu.types import DataType, Field, Schema
    from blaze_tpu.exprs import Col
    from blaze_tpu.exprs.ir import AggFn
    from blaze_tpu.parallel.sharded import DistAgg, DistributedGroupBy

    n_dev = len(jax.devices())
    cap = 64
    # deterministic GLOBAL input: every process can construct the whole
    # logical array, then keeps only its local shards
    rng = np.random.default_rng(7)
    keys_np = rng.integers(0, 13, (n_dev, cap)).astype(np.int64)
    vals_np = rng.integers(0, 100, (n_dev, cap)).astype(np.int64)
    rows_np = rng.integers(1, cap + 1, n_dev).astype(np.int32)

    def to_global(arr):
        return multihost_utils.host_local_array_to_global_array(
            arr, mesh, jax.sharding.PartitionSpec("data")
        )

    # host-local slice for this process (contiguous device blocks)
    per = n_dev // num_processes
    sl = slice(process_id * per, (process_id + 1) * per)
    keys = to_global(keys_np[sl])
    vals = to_global(vals_np[sl])
    rows = to_global(rows_np[sl])

    schema = Schema(
        [Field("k", DataType.int64()), Field("v", DataType.int64())]
    )
    gb = DistributedGroupBy(
        mesh, schema,
        keys=[Col("k")],
        aggs=[DistAgg(AggFn.SUM, Col("v")),
              DistAgg(AggFn.COUNT_STAR, None)],
        filter_pred=Col("v") >= 5,
    )
    key_out, agg_out, counts = gb([keys, vals], rows)

    from blaze_tpu.parallel.mesh import allgather_rows

    ko = allgather_rows(key_out, n_dev)
    so = allgather_rows(agg_out[0], n_dev)
    no = allgather_rows(agg_out[1], n_dev)
    cn = allgather_rows(counts, n_dev, trailing=False)

    # numpy reference over the full logical input
    ref: dict = {}
    for d in range(n_dev):
        for i in range(int(rows_np[d])):
            k, v = int(keys_np[d, i]), int(vals_np[d, i])
            if v >= 5:
                s, c = ref.get(k, (0, 0))
                ref[k] = (s + v, c + 1)
    got: dict = {}
    for d in range(n_dev):
        for g in range(int(cn[d])):
            k = int(ko[d, g])
            assert k not in got, "group owned by two devices"
            got[k] = (int(so[d, g]), int(no[d, g]))
    assert got == ref, (got, ref)
    print(
        json.dumps(
            {
                "process": process_id,
                "global_devices": n_dev,
                "groups": len(got),
                "ok": True,
            }
        ),
        flush=True,
    )
    return 0


def _worker_task_main(coordinator: str, num_processes: int,
                      process_id: int,
                      local_device_count: int) -> int:
    """Decoded-TaskDefinition workload: every rank decodes the SAME
    serialized task (rank-symmetric seed), execute_task applies the
    default mesh lowering (runtime/executor.decode_task), and the
    MeshGroupByExec runs as one SPMD program over the global
    2-process mesh. Each rank validates the union of all partitions
    against a numpy reference - proving the production task boundary,
    not just the raw collective, works across processes."""
    jax, mesh = initialize_worker(
        coordinator, num_processes, process_id,
        local_device_count=local_device_count,
        platform=os.environ.get("BLAZE_LAUNCH_PLATFORM") or None,
    )
    import numpy as np

    jax.config.update("jax_enable_x64", True)

    import pyarrow as pa

    from blaze_tpu.exprs import AggExpr, AggFn, Col
    from blaze_tpu.ops import AggMode, HashAggregateExec
    from blaze_tpu.ops.base import ExecContext
    from blaze_tpu.parallel.mesh_ops import MeshGroupByExec
    from blaze_tpu.plan.serde import task_to_proto
    from blaze_tpu.runtime.executor import (
        decode_task,
        execute_partition,
    )

    n_dev = len(jax.devices())
    rng = np.random.default_rng(21)
    n = 512
    k = rng.integers(0, 23, n).astype(np.int64)
    v = rng.integers(0, 1000, n).astype(np.int64)
    # a REAL serialized task needs a serializable scan: write the
    # deterministic table once (atomic rename - both ranks may race)
    import tempfile

    import pyarrow.parquet as pq

    from blaze_tpu.ops.parquet_scan import FileRange, ParquetScanExec

    path = os.path.join(
        tempfile.gettempdir(), "blz_launch_task_seed21.parquet"
    )
    if not os.path.exists(path):
        tmp = tempfile.NamedTemporaryFile(
            dir=tempfile.gettempdir(), suffix=".parquet",
            delete=False,
        )
        tmp.close()
        pq.write_table(pa.table({"k": k, "v": v}), tmp.name)
        os.replace(tmp.name, path)
    plan = HashAggregateExec(
        ParquetScanExec([[FileRange(path)]]),
        keys=[(Col("k"), "k")],
        aggs=[(AggExpr(AggFn.SUM, Col("v")), "s"),
              (AggExpr(AggFn.COUNT_STAR, None), "c")],
        mode=AggMode.COMPLETE,
    )
    blob = task_to_proto(plan, 0)

    # every rank decodes the SAME task (asserted by construction above:
    # one deterministic blob), so rank-symmetric collectives are safe -
    # attest it, because "auto" refuses to lower in a multi-process
    # group where ranks may hold different tasks
    os.environ["BLAZE_MESH_LOWERING"] = "on"
    ctx = ExecContext()
    op, _part = decode_task(blob, ctx)

    def find_mesh(o):
        if isinstance(o, MeshGroupByExec):
            return o
        for c in o.children:
            m = find_mesh(c)
            if m is not None:
                return m
        return None

    assert find_mesh(op) is not None, op.display()
    assert op.partition_count == 1, op.partition_count

    got = {}
    for p in range(op.partition_count):
        for rb in execute_partition(op, p, ctx):
            for kk, ss, cc in zip(
                rb.column("k").to_pylist(),
                rb.column("s").to_pylist(),
                rb.column("c").to_pylist(),
            ):
                assert kk not in got, "group owned by two partitions"
                got[int(kk)] = (int(ss), int(cc))
    ref = {}
    for kk, vv in zip(k, v):
        s, c = ref.get(int(kk), (0, 0))
        ref[int(kk)] = (s + int(vv), c + 1)
    assert got == ref, (len(got), len(ref))
    print(
        json.dumps(
            {
                "process": process_id,
                "global_devices": n_dev,
                "groups": len(got),
                "lowered": True,
                "ok": True,
            }
        ),
        flush=True,
    )
    return 0


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def launch_local(num_processes: int = 2, devices_per_process: int = 4,
                 port: Optional[int] = None, timeout: float = 300.0,
                 workload: str = "groupby"):
    """Spawn num_processes local workers (one-per-host stand-in); each
    contributes devices_per_process virtual CPU devices to the global
    mesh. Returns the list of per-process JSON results. Fails FAST with
    the real worker error: a crashed rank leaves its peers blocked in
    the distributed barrier, so the driver polls all ranks instead of
    waiting out the timeout on rank order."""
    import time as _time

    if port is None:
        port = _free_port()  # fixed ports collide across racing runs
    repo = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["BLAZE_LAUNCH_PLATFORM"] = "cpu"
    env.pop("XLA_FLAGS", None)
    import tempfile

    procs = []
    logs = []
    for pid in range(num_processes):
        # file-backed stdout: a chatty worker can never fill a pipe
        # buffer and deadlock the barrier
        log = tempfile.NamedTemporaryFile(
            mode="w+", prefix=f"blz-launch-{pid}-", suffix=".log",
            delete=False,
        )
        logs.append(log)
        procs.append(
            subprocess.Popen(
                [
                    sys.executable, "-m",
                    "blaze_tpu.runtime.launcher",
                    f"127.0.0.1:{port}", str(num_processes), str(pid),
                    str(devices_per_process), workload,
                ],
                env=env,
                stdout=log,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )

    def read_log(i: int) -> str:
        logs[i].flush()
        with open(logs[i].name) as f:
            return f.read()

    try:
        deadline = _time.monotonic() + timeout
        pending = set(range(num_processes))
        while pending and _time.monotonic() < deadline:
            for i in sorted(pending):
                rc = procs[i].poll()
                if rc is None:
                    continue
                pending.discard(i)
                if rc != 0:
                    raise RuntimeError(
                        f"worker {i} failed:\n" + read_log(i)[-2000:]
                    )
            if pending:
                _time.sleep(0.05)
        if pending:
            raise TimeoutError(
                f"workers {sorted(pending)} still running after "
                f"{timeout}s"
            )
        results = []
        for i in range(num_processes):
            for line in reversed(read_log(i).splitlines()):
                if line.startswith("{"):
                    results.append(json.loads(line))
                    break
        return results
    finally:
        # never orphan workers blocked in the distributed barrier
        for p in procs:
            if p.poll() is None:
                p.kill()
        for log in logs:
            log.close()
            try:
                os.unlink(log.name)
            except OSError:
                pass


if __name__ == "__main__":
    _main = (
        _worker_task_main
        if len(sys.argv) > 5 and sys.argv[5] == "task"
        else _worker_main
    )
    raise SystemExit(
        _main(
            sys.argv[1], int(sys.argv[2]), int(sys.argv[3]),
            int(sys.argv[4]),
        )
    )
