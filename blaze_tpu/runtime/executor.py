"""Task executor: the engine's entry point.

Reference counterpart: the JNI entry `callNative` (exec.rs:118-328) -
decode a TaskDefinition, build the operator tree, execute one partition,
stream Arrow batches back, then push collected metrics. Here the embedding
is in-process Python instead of JNI, and the batch handshake is a plain
iterator instead of the SynchronousQueue rendezvous (NativeSupports.scala:
237-323) - XLA's async dispatch already overlaps host and device work.

Failure semantics follow the reference (SURVEY 5.3): operator errors are
wrapped with task context into TaskExecutionError and propagate cleanly to
the embedder; partial output is never silently dropped.
"""

from __future__ import annotations

import logging
from typing import Iterator, List, Optional

import pyarrow as pa

from blaze_tpu.batch import ColumnBatch
from blaze_tpu.errors import ErrorClass, classify
from blaze_tpu.obs import trace as obs_trace
from blaze_tpu.ops.base import ExecContext, MetricNode, PhysicalOp
from blaze_tpu.ops.util import ensure_compacted
from blaze_tpu.testing import chaos

log = logging.getLogger("blaze_tpu.executor")


def _process_count() -> int:
    """jax.process_count without forcing backend init side effects
    beyond what execution needs anyway."""
    import jax

    try:
        return jax.process_count()
    except Exception:  # noqa: BLE001 - uninitialized distributed
        return 1


class TaskExecutionError(RuntimeError):
    def __init__(self, task_id: str, partition: int, cause: BaseException):
        super().__init__(
            f"task {task_id} partition {partition} failed: {cause!r}"
        )
        self.task_id = task_id
        self.partition = partition
        self.__cause__ = cause

    @property
    def error_class(self) -> ErrorClass:
        """Failure taxonomy class of the wrapped cause (the raise-site
        classification the scheduler's retry policy keys on)."""
        return classify(self)


def prepare_decoded_task(decoded, ctx: ExecContext):
    """Shared decode tail for every wire format (engine-native and
    reference-compat): fuse the tree exactly like driver-built plans
    (decoded tasks are the production entry, so they must hit the same
    one-dispatch pipeline programs; reference: the decoded plan IS the
    executed plan, exec.rs:137-165), attach scan hints, and install the
    task's resources into the context."""
    from blaze_tpu.ops.fused import fuse_pipelines
    from blaze_tpu.planner.colprune import install as install_scan_hints

    op, partition, task_id, resources = decoded
    # Mesh lowering first (it matches raw aggregate shapes the fusion
    # rewrite would consume): with >1 visible device, eligible root
    # shapes become one pjit program over the ICI mesh - the
    # cost-guarded pass in planner/distribute.lower_plan_to_mesh.
    # ONLY single-partition plans qualify at this boundary: a
    # TaskDefinition carries ONE partition of its stage, and the SPMD
    # operators consume the WHOLE child - lowering a multi-partition
    # task would double-count its siblings' data. The lowered tree is
    # coalesced so the task's one partition carries every group (the
    # mesh ops' output is per-device disjoint). Mode resolution:
    # ctx.mesh_mode (the serving tier's knob) > BLAZE_MESH_LOWERING
    # env > "auto". "auto" lowers only in a single-controller process
    # (in a multi-process group, ranks decode DIFFERENT tasks - the
    # task-per-partition cluster model - and a one-sided collective
    # would deadlock the group); "on" forces (asserts the caller
    # decodes rank-symmetric tasks - the launcher's SPMD workload);
    # "off" disables. Root-only: a mid-tree rewrite would change the
    # partitioning under Sort/Limit/Window parents.
    from blaze_tpu.planner.distribute import (
        lower_plan_to_mesh,
        resolve_mesh_mode,
    )

    mode = resolve_mesh_mode(ctx)
    lower_ok = mode == "on" or (
        mode == "auto" and _process_count() == 1
    )
    if lower_ok and op.partition_count == 1:
        from blaze_tpu.ops.union import CoalescePartitionsExec

        lowered = lower_plan_to_mesh(op, mode=mode)
        op = (
            CoalescePartitionsExec(lowered)
            if lowered.partition_count != 1
            else lowered
        )
    op = fuse_pipelines(op)
    # freshly-decoded tree: scans are private to this task, so filter
    # pushdown (not just column pruning) is safe to attach
    install_scan_hints(op, with_filters=True)
    ctx.partition_id = partition
    ctx.task_id = task_id
    for rid, provider in resources.items():
        ctx.resources.setdefault(rid, provider)
    return op, partition


def decode_task(task_bytes: bytes, ctx: ExecContext):
    """Decode engine-native TaskDefinition bytes into a runnable
    (op, partition) pair.

    Mesh lowering happens inside prepare_decoded_task (before fusion),
    so every wire format shares it."""
    from blaze_tpu.plan.serde import task_from_proto

    return prepare_decoded_task(task_from_proto(task_bytes), ctx)


def execute_task(task_bytes: bytes,
                 ctx: Optional[ExecContext] = None
                 ) -> Iterator[pa.RecordBatch]:
    """Decode and run one serialized TaskDefinition; yields Arrow batches
    (the FFI-equivalent boundary, exec.rs:205-255)."""
    ctx = ctx or ExecContext()
    op, partition = decode_task(task_bytes, ctx)
    yield from execute_partition(op, partition, ctx)


def execute_partition(op: PhysicalOp, partition: int, ctx: ExecContext
                      ) -> Iterator[pa.RecordBatch]:
    from blaze_tpu.planner.colprune import install as install_scan_hints

    # column pruning for driver-built plans too (required sets only
    # union-grow, so scans shared across plans stay correct; filters are
    # reserved for the fresh-tree decode path)
    install_scan_hints(op)
    if log.isEnabledFor(logging.DEBUG):
        log.debug(
            "executing task %s partition %d:\n%s",
            ctx.task_id, partition, op.display(),
        )
    from blaze_tpu.runtime import dispatch

    counter = dispatch.counting()
    counter.__enter__()
    # obs seam: one span per partition drain (child spans - parquet
    # decode, H2D, kernel dispatch - attach under it via the
    # thread-current stack; the off path is one attribute check)
    span_cm = (
        obs_trace.span(
            "execute_partition", rec=ctx.tracer,
            partition=partition, task=ctx.task_id,
        )
        if obs_trace.ACTIVE else obs_trace.NULL
    )
    try:
        with span_cm:
            if chaos.ACTIVE:
                # the generic per-partition fault seam (chaos harness);
                # inside the try so an injected fault is classified and
                # wrapped exactly like a real operator failure
                chaos.fire(
                    "task.execute", partition=partition,
                    task_id=ctx.task_id,
                )
            for cb in op.execute(partition, ctx):
                cb = ensure_compacted(cb)
                if cb.num_rows == 0:
                    continue
                rb = cb.to_arrow()
                ctx.metrics.add("output_rows", rb.num_rows)
                ctx.metrics.add("output_batches", 1)
                yield rb
    except (KeyboardInterrupt, GeneratorExit):
        # task cancellation must not poison the engine (the reference
        # swallows JVM-interrupts the same way, exec.rs:330-343)
        log.info("task %s partition %d cancelled", ctx.task_id, partition)
        raise
    except Exception as e:
        raise TaskExecutionError(ctx.task_id, partition, e) from e
    finally:
        # per-task dispatch/transfer/kernel-cache accounting in the
        # metric tree (delta of the process-global counters, so
        # concurrent tasks in other threads land here too - same
        # caveat as dispatch.counting itself)
        counter.__exit__(None, None, None)
        for k, v in counter.counts.items():
            ctx.metrics.add("dispatch." + k, v)


def run_plan(op: PhysicalOp, ctx: Optional[ExecContext] = None
             ) -> pa.Table:
    """Run every partition and collect one Arrow table (driver-side
    convenience; partitions share the context/resource registry)."""
    ctx = ctx or ExecContext()
    batches: List[pa.RecordBatch] = []
    schema = None
    for p in range(op.partition_count):
        for rb in execute_partition(op, p, ctx):
            if schema is None:
                schema = rb.schema
            batches.append(rb)
    if schema is None:
        from blaze_tpu.types import to_arrow_schema

        return pa.Table.from_batches([], to_arrow_schema(op.schema))
    aligned = []
    for rb in batches:
        if rb.schema != schema:
            rb = rb.cast(schema)
        aligned.append(rb)
    return pa.Table.from_batches(aligned, schema)
