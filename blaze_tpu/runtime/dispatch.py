"""Device-dispatch accounting and the global kernel cache.

Why this exists (reference parity + TPU reality): the reference engine's
hot loop is one native call per *task* (exec.rs:196-255) - operators fuse
into a single streamed program, so per-query overhead is O(1) calls. An
XLA engine pays per *dispatch* (jit call, eager op, H2D/D2H transfer);
when the chip is network-attached each dispatch costs a round trip, so
dispatch count IS the end-to-end performance model for small/medium
queries. This module makes that count observable (per-query logging in
benchmarks, regression tests) and provides the process-wide kernel cache
so freshly-built plans (a new plan object per query, as in the reference's
per-task plan decode) reuse compiled executables instead of re-tracing.

Counters are process-global and thread-safe-enough (GIL increments); the
scheduler's worker threads all contribute to the same totals, which is
what a per-query report wants.
"""

from __future__ import annotations

import collections
import threading
from typing import Any, Callable, Dict, Tuple

import jax

_lock = threading.Lock()
_counts: Dict[str, int] = {}

# process-wide compiled-kernel cache: structural key -> wrapped jit fn.
# Keys must capture everything that changes the traced program: op kind,
# bound expression trees (ir.Expr is structurally hashable), schema dtype
# descriptors, buffer layout, static config (capacities, modes).
# LRU-bounded: a long-lived worker seeing many structurally distinct
# queries must not accumulate executables forever (per-plan caches used
# to die with the plan object; this is the global replacement).
_KERNELS: "collections.OrderedDict[Tuple, Callable]" = (
    collections.OrderedDict()
)
# Bounded for executable memory. Entries evicted LRU recompile
# transparently. BLAZE_KERNEL_CACHE_CAP overrides (0 = unbounded).
import os as _os

_KERNEL_CACHE_CAP = int(
    _os.environ.get("BLAZE_KERNEL_CACHE_CAP", 256)
) or (1 << 30)

# ---------------------------------------------------------------------------
# Per-kernel XLA:CPU runtime selection.
#
# jaxlib's default CPU runtime (the "thunk" runtime) serializes scatter
# updates through a slow per-element path: an 8M-row segment_sum costs
# ~457ms vs ~33ms under the legacy runtime (measured on this host,
# jaxlib 0.4.36) - a 14x gap that dominates every scatter-core grouped
# aggregate and hash-table insert. The legacy runtime, in turn, sorts
# ~6x SLOWER, so the selection must be per-kernel: scatter-dominated
# kernels (grouped aggregation, join table inserts, the fused
# join+aggregate program) opt in via `cached_kernel(...,
# scatter_class=True)`; sort-dominated kernels (window, lexsort
# grouping, the sorted join core) keep the default runtime.
#
# CPU-only: on TPU (and any non-CPU backend) the hint is a no-op. The
# option is probed once with a throwaway compile so an incompatible
# jaxlib silently falls back to the default runtime.
# BLAZE_CPU_RUNTIME_SPLIT=0 disables the split entirely.
_SCATTER_JIT_KWARGS: Dict[str, Any] = None


def _scatter_jit_kwargs() -> Dict[str, Any]:
    global _SCATTER_JIT_KWARGS
    if _SCATTER_JIT_KWARGS is not None:
        return _SCATTER_JIT_KWARGS
    kwargs: Dict[str, Any] = {}
    if _os.environ.get("BLAZE_CPU_RUNTIME_SPLIT", "1") != "0":
        try:
            if jax.default_backend() == "cpu":
                opts = {"xla_cpu_use_thunk_runtime": False}
                # probe compile: rejects on jaxlibs without the flag
                jax.jit(
                    lambda x: x + 1, compiler_options=opts
                )(0)
                kwargs = {"compiler_options": opts}
        except Exception:
            kwargs = {}
    _SCATTER_JIT_KWARGS = kwargs
    return kwargs


def record(kind: str, n: int = 1) -> None:
    with _lock:
        _counts[kind] = _counts.get(kind, 0) + n


def snapshot() -> Dict[str, int]:
    with _lock:
        return dict(_counts)


def reset() -> Dict[str, int]:
    """Return current counts and zero them (per-query measurement)."""
    global _counts
    with _lock:
        out = _counts
        _counts = {}
        return out


class counting:
    """Context manager: `with counting() as c: ...; c.counts` gives the
    dispatch/transfer counts attributable to the block (delta of the
    global counters; concurrent tasks in other threads also land here)."""

    def __enter__(self):
        self._start = snapshot()
        self.counts: Dict[str, int] = {}
        return self

    def __exit__(self, *exc):
        end = snapshot()
        self.counts = {
            k: v - self._start.get(k, 0)
            for k, v in end.items()
            if v - self._start.get(k, 0)
        }
        return False


def _wrap_dispatch(fn: Callable, kind: str,
                   span: str = "kernel_dispatch") -> Callable:
    from blaze_tpu.obs import trace as obs_trace
    from blaze_tpu.testing import chaos

    def wrapped(*args, **kw):
        if chaos.ACTIVE:
            # chaos seam: a compiled-kernel invocation that throws
            # (device reset, interconnect error) - off path is one
            # module-attribute load
            chaos.fire("kernel.dispatch", kind=kind)
        record(kind)
        if obs_trace.ACTIVE:
            # obs seam: one span per kernel dispatch (the unit of the
            # perf model); no-op when no recorder is in scope. XLA
            # dispatch is async, so this measures launch, not device
            # occupancy - the span COUNT is the signal. `span` gives
            # relational-core kernels (join/group) their own phase
            # attribution in obs/phases.py.
            with obs_trace.span(span, kind=kind):
                return fn(*args, **kw)
        return fn(*args, **kw)

    return wrapped


def cached_kernel(key: Tuple, build: Callable[[], Callable],
                  scatter_class: bool = False,
                  span: str = "kernel_dispatch",
                  **jit_kwargs) -> Callable:
    """Process-wide compiled-kernel lookup.

    `build()` returns the python function to jit; it runs only on cache
    miss. Each invocation of the returned callable records one
    "dispatches" count (steady state: one XLA execution per call).

    `scatter_class=True` marks a scatter-dominated kernel: on the CPU
    backend it compiles under the legacy (non-thunk) XLA:CPU runtime
    (see _scatter_jit_kwargs). `span` names the obs trace span so
    phases.py can band join/group dispatches separately."""
    with _lock:
        fn = _KERNELS.get(key)
        if fn is not None:
            _KERNELS.move_to_end(key)
            # cache-hit accounting (vs kernel_builds): a steady-state
            # query stream should be all hits - tests pin this
            _counts["kernel_hits"] = _counts.get("kernel_hits", 0) + 1
    if fn is None:
        if scatter_class:
            jit_kwargs = {**_scatter_jit_kwargs(), **jit_kwargs}
        with _lock:
            fn = _KERNELS.get(key)
            if fn is None:
                # inline count: record() would re-take the
                # non-reentrant lock
                _counts["kernel_builds"] = (
                    _counts.get("kernel_builds", 0) + 1
                )
                fn = _wrap_dispatch(
                    jax.jit(build(), **jit_kwargs), "dispatches",
                    span=span,
                )
                _KERNELS[key] = fn
                while len(_KERNELS) > _KERNEL_CACHE_CAP:
                    _KERNELS.popitem(last=False)
    return fn


def kernel_cache_size() -> int:
    return len(_KERNELS)


def clear_kernel_cache() -> None:
    _KERNELS.clear()


def task_threads(n_tasks: int, cap: int = 4) -> int:
    """Concurrency for device-dispatching task pools (exchange map
    stages, the scheduler). One process shares one device, so threads
    buy IO/encode overlap, not compute throughput. BLAZE_TASK_THREADS
    overrides (set to 1 to serialize every device-touching task - the
    workaround for jaxlib CPU-client races under concurrent
    compilation, see tests/conftest.py)."""
    import os

    env = os.environ.get("BLAZE_TASK_THREADS")
    if env:
        cap = max(1, int(env))
    return min(cap, max(1, n_tasks))


def device_get(tree: Any) -> Any:
    """One batched D2H fetch (counted once - the transfers pipeline)."""
    record("d2h_fetches")
    return jax.device_get(tree)


def host_int(x) -> int:
    """Blocking scalar readback (a full device round trip)."""
    record("d2h_syncs")
    return int(x)
