"""Local task scheduler: concurrent partition execution with retries.

Plays Spark's executor role for standalone/local runs, the way the
reference's TPC-DS CI exercises its whole distributed path with local-mode
Spark (SURVEY 4): partitions run as tasks on a thread pool (device
dispatch is async so threads overlap host decode/IPC work with device
compute), failed tasks retry like Spark's task retry (SURVEY 5.3), results
stream back in partition order.

Failure semantics: the FIRST task to exhaust its retries fails the plan
immediately - outstanding sibling tasks are cancelled (queued ones never
start; running ones observe the cancel event at their next batch
boundary and unwind through the executor's GeneratorExit cancellation
pass-through, runtime/executor.py), instead of running to completion
against a plan that already failed.
"""

from __future__ import annotations

import concurrent.futures as cf
import logging
import threading
from typing import List, Optional

import pyarrow as pa

from blaze_tpu.ops.base import ExecContext, PhysicalOp
from blaze_tpu.runtime.executor import TaskExecutionError, execute_partition

log = logging.getLogger("blaze_tpu.scheduler")


class PlanCancelled(RuntimeError):
    """A sibling task failed (or the caller cancelled the plan); this
    partition's work was abandoned cooperatively."""


def run_plan_parallel(
    op: PhysicalOp,
    ctx: Optional[ExecContext] = None,
    parallelism: int = 4,
    max_attempts: int = 3,
    cancel: Optional[threading.Event] = None,
) -> pa.Table:
    """Execute every partition on a thread pool and collect one table.

    `cancel` lets an embedder (the serving tier) abort the whole plan
    cooperatively. Fail-fast uses a separate INTERNAL event so a task
    failure never mutates the caller's (possibly shared) event."""
    ctx = ctx or ExecContext()
    abort = threading.Event()  # internal: first-failure fail-fast

    def cancelled() -> bool:
        return abort.is_set() or (
            cancel is not None and cancel.is_set()
        )

    def task(p: int) -> List[pa.RecordBatch]:
        last: Optional[BaseException] = None
        for attempt in range(max_attempts):
            if cancelled():
                raise PlanCancelled(f"partition {p} cancelled")
            it = execute_partition(op, p, ctx)
            out: List[pa.RecordBatch] = []
            try:
                for rb in it:
                    out.append(rb)
                    if cancelled():
                        # the executor's cancellation pass-through:
                        # close -> GeneratorExit unwinds the operator
                        # tree without poisoning the engine
                        it.close()
                        raise PlanCancelled(
                            f"partition {p} cancelled mid-stream"
                        )
                return out
            except PlanCancelled:
                raise
            except TaskExecutionError as e:
                last = e
                ctx.metrics.add("task_retries", 1)
                log.warning(
                    "task for partition %d failed (attempt %d): %s",
                    p, attempt + 1, e,
                )
            finally:
                it.close()
        raise last  # type: ignore[misc]

    n = op.partition_count
    results: List[List[pa.RecordBatch]] = [[] for _ in range(n)]
    from blaze_tpu.runtime.dispatch import task_threads

    first_error: Optional[BaseException] = None
    with cf.ThreadPoolExecutor(
        max_workers=task_threads(n, cap=max(1, parallelism))
    ) as pool:
        futs = {pool.submit(task, p): p for p in range(n)}
        for fut in cf.as_completed(futs):
            try:
                results[futs[fut]] = fut.result()
            except PlanCancelled:
                continue  # secondary casualty of the first failure
            except BaseException as e:  # noqa: BLE001 - re-raised below
                if first_error is None:
                    first_error = e
                    # fail fast: queued siblings never start, running
                    # ones observe the event at the next batch
                    abort.set()
                    for f in futs:
                        f.cancel()
    if first_error is not None:
        raise first_error
    if cancel is not None and cancel.is_set():
        raise PlanCancelled("plan cancelled by caller")
    batches = [rb for part in results for rb in part]
    if not batches:
        from blaze_tpu.types import to_arrow_schema

        return pa.Table.from_batches([], to_arrow_schema(op.schema))
    schema = batches[0].schema
    aligned = [
        rb if rb.schema == schema else rb.cast(schema) for rb in batches
    ]
    return pa.Table.from_batches(aligned, schema)
