"""Local task scheduler: concurrent partition execution with
CLASSIFIED retries.

Plays Spark's executor role for standalone/local runs, the way the
reference's TPC-DS CI exercises its whole distributed path with local-mode
Spark (SURVEY 4): partitions run as tasks on a thread pool (device
dispatch is async so threads overlap host decode/IPC work with device
compute), failed tasks retry like Spark's task retry (SURVEY 5.3), results
stream back in partition order.

Failure semantics (blaze_tpu/errors.py taxonomy):

  TRANSIENT           retried up to max_attempts with exponential
                      backoff + jitter (immediate re-runs hammered the
                      same flaky resource and burned budget in bursts)
  RESOURCE_EXHAUSTED  degraded: the partition re-executes through the
                      pandas host engine (planner/host_engine.py) -
                      the native->Spark fallback analog; the metric
                      tree records `degraded_partitions`
  PLAN_INVALID /      fail fast, zero retries - deterministic failures
  INTERNAL            don't get cheaper the second time
  CANCELLED           cooperative unwind, never counted as failure

The FIRST task to fail fatally fails the plan immediately - outstanding
sibling tasks are cancelled (queued ones never start; running ones
observe the cancel event at their next batch boundary and unwind through
the executor's GeneratorExit cancellation pass-through,
runtime/executor.py), instead of running to completion against a plan
that already failed.
"""

from __future__ import annotations

import concurrent.futures as cf
import logging
import random
import threading
import time
from typing import Callable, List, Optional

import pyarrow as pa

from blaze_tpu.errors import ErrorClass, classify, retry_action
from blaze_tpu.obs import trace as obs_trace
from blaze_tpu.ops.base import ExecContext, PhysicalOp
from blaze_tpu.runtime.executor import TaskExecutionError, execute_partition

log = logging.getLogger("blaze_tpu.scheduler")


class PlanCancelled(RuntimeError):
    """A sibling task failed (or the caller cancelled the plan); this
    partition's work was abandoned cooperatively."""


def backoff_delay(attempt: int, base_s: float = 0.05,
                  cap_s: float = 2.0) -> float:
    """Exponential backoff with full jitter: uniform in
    (0, min(cap, base * 2^attempt)]. Jitter decorrelates retries from
    concurrent failed tasks - without it every sibling re-hits the
    flaky resource in lockstep."""
    hi = min(cap_s, base_s * (2 ** attempt))
    return random.uniform(hi * 0.5, hi)


def run_plan_parallel(
    op: PhysicalOp,
    ctx: Optional[ExecContext] = None,
    parallelism: int = 4,
    max_attempts: int = 3,
    cancel: Optional[threading.Event] = None,
    retry_backoff_s: float = 0.05,
    degrade_to_host: bool = True,
    on_attempt: Optional[Callable[[dict], None]] = None,
    mesh: Optional[str] = None,
) -> pa.Table:
    """Execute every partition on a thread pool and collect one table.

    `cancel` lets an embedder (the serving tier) abort the whole plan
    cooperatively. Fail-fast uses a separate INTERNAL event so a task
    failure never mutates the caller's (possibly shared) event.
    `on_attempt` observes every failed attempt as a dict
    {partition, attempt, error_class, error, action} - an embedder's
    hook into the failure journal. (The serving tier drives partitions
    itself for cache interleaving, so it applies the SAME policy via
    errors.retry_action rather than calling this function.)

    `mesh` selects the mesh execution tier for this plan ("auto" cost-
    guarded, "on" forced, "off"/None single-device - driver plans stay
    single-device by default): the root is lowered through
    planner/distribute.lower_plan_to_mesh, partitions then map one-per-
    device, and a mesh failure degrades back to the single-device plan
    (docs/MESH.md)."""
    ctx = ctx or ExecContext()
    if mesh is not None and mesh != "off":
        from blaze_tpu.planner.distribute import lower_plan_to_mesh

        ctx.mesh_mode = mesh
        op = lower_plan_to_mesh(op, mode=mesh)
    abort = threading.Event()  # internal: first-failure fail-fast

    def cancelled() -> bool:
        return abort.is_set() or (
            cancel is not None and cancel.is_set()
        )

    def note(p: int, attempt: int, ec: ErrorClass, e: BaseException,
             action: str) -> None:
        if on_attempt is not None:
            on_attempt({
                "partition": p, "attempt": attempt,
                "error_class": ec.value, "error": str(e)[:300],
                "action": action,
            })

    def degrade(p: int, cause: BaseException) -> List[pa.RecordBatch]:
        """RESOURCE_EXHAUSTED: re-run the partition on the host engine
        (graceful degradation). Raises the ORIGINAL error when the
        tree has no host mapping."""
        from blaze_tpu.planner.host_engine import execute_partition_host

        try:
            with (obs_trace.span("host_degrade", rec=ctx.tracer,
                                 partition=p)
                  if obs_trace.ACTIVE else obs_trace.NULL):
                out = execute_partition_host(op, p, ctx)
        except Exception as host_err:  # noqa: BLE001 - original wins
            log.warning(
                "host degradation of partition %d unavailable (%s); "
                "surfacing original error", p, host_err,
            )
            raise cause
        ctx.metrics.add("degraded_partitions", 1)
        log.warning(
            "partition %d degraded to host engine after "
            "RESOURCE_EXHAUSTED: %s", p, cause,
        )
        return out

    def task(p: int) -> List[pa.RecordBatch]:
        for attempt in range(max_attempts):
            if cancelled():
                raise PlanCancelled(f"partition {p} cancelled")
            # obs seam: ONE span per attempt (retries each get their
            # own, auto-tagged with error_class on failure); the
            # executor's per-partition span nests under it via the
            # thread-current stack
            span_cm = (
                obs_trace.span("attempt", rec=ctx.tracer,
                               partition=p, attempt=attempt)
                if obs_trace.ACTIVE else obs_trace.NULL
            )
            it = execute_partition(op, p, ctx)
            out: List[pa.RecordBatch] = []
            try:
                with span_cm:
                    for rb in it:
                        out.append(rb)
                        if cancelled():
                            # the executor's cancellation
                            # pass-through: close -> GeneratorExit
                            # unwinds the operator tree without
                            # poisoning the engine
                            it.close()
                            raise PlanCancelled(
                                f"partition {p} cancelled mid-stream"
                            )
                    return out
            except PlanCancelled:
                raise
            except TaskExecutionError as e:
                if out:
                    # drop the abandoned attempt's partial output from
                    # the counters; a retry/degrade re-counts it
                    ctx.metrics.add(
                        "output_rows",
                        -sum(rb.num_rows for rb in out),
                    )
                    ctx.metrics.add("output_batches", -len(out))
                ec = classify(e)
                action = retry_action(
                    ec, attempt, max_attempts, degrade_to_host
                )
                if action == "cancel":
                    raise PlanCancelled(
                        f"partition {p} cancelled in-task"
                    ) from e
                note(p, attempt, ec, e, action)
                if action == "degrade":
                    return degrade(p, e)
                if action == "fail":
                    raise
                ctx.metrics.add("task_retries", 1)
                ctx.metrics.add("retries.transient", 1)
                log.warning(
                    "task for partition %d failed transiently "
                    "(attempt %d): %s; backing off", p, attempt + 1, e,
                )
                # interruptible backoff: a sibling failure wakes the
                # abort.wait immediately; the caller's cancel event is
                # a separate object, so poll it on a short tick - the
                # loop-top cancelled() check then unwinds
                wake_at = time.monotonic() + backoff_delay(
                    attempt, retry_backoff_s
                )
                while not cancelled():
                    left = wake_at - time.monotonic()
                    if left <= 0:
                        break
                    abort.wait(min(0.05, left))
            finally:
                it.close()
        raise AssertionError("unreachable: attempt loop fell through")

    n = op.partition_count
    results: List[List[pa.RecordBatch]] = [[] for _ in range(n)]
    from blaze_tpu.runtime.dispatch import task_threads

    first_error: Optional[BaseException] = None
    with cf.ThreadPoolExecutor(
        max_workers=task_threads(n, cap=max(1, parallelism))
    ) as pool:
        futs = {pool.submit(task, p): p for p in range(n)}
        for fut in cf.as_completed(futs):
            try:
                results[futs[fut]] = fut.result()
            except PlanCancelled:
                continue  # secondary casualty of the first failure
            except BaseException as e:  # noqa: BLE001 - re-raised below
                if first_error is None:
                    first_error = e
                    # fail fast: queued siblings never start, running
                    # ones observe the event at the next batch
                    abort.set()
                    for f in futs:
                        f.cancel()
    if first_error is not None:
        raise first_error
    if cancel is not None and cancel.is_set():
        raise PlanCancelled("plan cancelled by caller")
    batches = [rb for part in results for rb in part]
    if not batches:
        from blaze_tpu.types import to_arrow_schema

        return pa.Table.from_batches([], to_arrow_schema(op.schema))
    schema = batches[0].schema
    aligned = [
        rb if rb.schema == schema else rb.cast(schema) for rb in batches
    ]
    return pa.Table.from_batches(aligned, schema)
