"""Local task scheduler: concurrent partition execution with retries.

Plays Spark's executor role for standalone/local runs, the way the
reference's TPC-DS CI exercises its whole distributed path with local-mode
Spark (SURVEY 4): partitions run as tasks on a thread pool (device
dispatch is async so threads overlap host decode/IPC work with device
compute), failed tasks retry like Spark's task retry (SURVEY 5.3), results
stream back in partition order."""

from __future__ import annotations

import concurrent.futures as cf
import logging
from typing import List, Optional

import pyarrow as pa

from blaze_tpu.ops.base import ExecContext, PhysicalOp
from blaze_tpu.runtime.executor import TaskExecutionError, execute_partition

log = logging.getLogger("blaze_tpu.scheduler")


def run_plan_parallel(
    op: PhysicalOp,
    ctx: Optional[ExecContext] = None,
    parallelism: int = 4,
    max_attempts: int = 3,
) -> pa.Table:
    """Execute every partition on a thread pool and collect one table."""
    ctx = ctx or ExecContext()

    def task(p: int) -> List[pa.RecordBatch]:
        last: Optional[BaseException] = None
        for attempt in range(max_attempts):
            try:
                return list(execute_partition(op, p, ctx))
            except TaskExecutionError as e:
                last = e
                ctx.metrics.add("task_retries", 1)
                log.warning(
                    "task for partition %d failed (attempt %d): %s",
                    p, attempt + 1, e,
                )
        raise last  # type: ignore[misc]

    n = op.partition_count
    results: List[List[pa.RecordBatch]] = [[] for _ in range(n)]
    from blaze_tpu.runtime.dispatch import task_threads

    with cf.ThreadPoolExecutor(
        max_workers=task_threads(n, cap=max(1, parallelism))
    ) as pool:
        futs = {pool.submit(task, p): p for p in range(n)}
        for fut in cf.as_completed(futs):
            results[futs[fut]] = fut.result()
    batches = [rb for part in results for rb in part]
    if not batches:
        from blaze_tpu.types import to_arrow_schema

        return pa.Table.from_batches([], to_arrow_schema(op.schema))
    schema = batches[0].schema
    aligned = [
        rb if rb.schema == schema else rb.cast(schema) for rb in batches
    ]
    return pa.Table.from_batches(aligned, schema)
