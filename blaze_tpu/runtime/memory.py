"""Memory budget + spill ladder.

Reference counterpart: DataFusion's MemoryConsumer/try_grow/spill protocol
wired through MemoryManagerConfig {max_memory, memory_fraction}
(exec.rs:79-94; spill path shuffle_writer_exec.rs:570-623). The TPU engine
extends the ladder one level: device HBM -> host RAM -> disk (SURVEY 7
"spill & memory ladder") - operators materialize on device, overflow to
host buffers tracked here, and spill those to disk under pressure.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List

from blaze_tpu.config import get_config
from blaze_tpu.testing import chaos


class MemoryPool:
    """Tracks host-side buffered bytes; triggers consumer spills when the
    budget (max_memory * memory_fraction) is exceeded. Spill order is
    largest-consumer-first like DataFusion's."""

    def __init__(self, budget: int = None):
        cfg = get_config()
        self.budget = budget if budget is not None else int(
            cfg.max_memory * cfg.memory_fraction
        )
        self._used: Dict[int, int] = {}
        self._spill_fns: Dict[int, Callable[[], int]] = {}
        self._lock = threading.Lock()
        self.spill_count = 0
        self.spilled_bytes = 0

    def register(self, consumer_id: int, spill: Callable[[], int]) -> None:
        with self._lock:
            self._used.setdefault(consumer_id, 0)
            self._spill_fns[consumer_id] = spill

    def unregister(self, consumer_id: int) -> None:
        with self._lock:
            self._used.pop(consumer_id, None)
            self._spill_fns.pop(consumer_id, None)

    def total_used(self) -> int:
        with self._lock:
            return sum(self._used.values())

    def grow(self, consumer_id: int, nbytes: int) -> None:
        """Account nbytes to the consumer; spill others (or it) if needed."""
        with self._lock:
            self._used[consumer_id] = self._used.get(consumer_id, 0) + nbytes
            over = sum(self._used.values()) - self.budget
            victims: List[int] = []
            if over > 0:
                victims = sorted(
                    self._used, key=lambda c: -self._used[c]
                )
        if over > 0:
            freed = 0
            for v in victims:
                fn = self._spill_fns.get(v)
                if fn is None:
                    continue
                released = fn()
                with self._lock:
                    self._used[v] = max(0, self._used[v] - released)
                self.spill_count += 1
                self.spilled_bytes += released
                freed += released
                if freed >= over:
                    break

    def shrink(self, consumer_id: int, nbytes: int) -> None:
        with self._lock:
            self._used[consumer_id] = max(
                0, self._used.get(consumer_id, 0) - nbytes
            )


class DeviceMemoryTracker:
    """Live DEVICE (HBM) bytes per operator - the accounting the spill
    ladder's top rung runs on. Materializing operators (joins,
    aggregates, sorts) register what they hold resident; sizing
    decisions (external bucket counts, materialize-vs-stream) read the
    budget headroom instead of guessing (reference role:
    MemoryManagerConfig feeding DataFusion consumers, exec.rs:79-94)."""

    def __init__(self, budget: int = None):
        self._budget_override = budget
        self._used: Dict[int, int] = {}
        self._lock = threading.Lock()
        self.high_water = 0

    @property
    def budget(self) -> int:
        if self._budget_override is not None:
            return self._budget_override
        # live read: the process-global tracker must follow config swaps
        return int(get_config().device_memory_budget)

    def track(self, op_id: int, nbytes: int) -> None:
        if chaos.ACTIVE:
            # chaos seam: device-memory-pressure at the HBM accounting
            # boundary (a RESOURCE_EXHAUSTED fault here drives the
            # host-engine degradation path)
            chaos.fire("device.memory", op_id=op_id, nbytes=nbytes)
        with self._lock:
            self._used[op_id] = self._used.get(op_id, 0) + nbytes
            self.high_water = max(self.high_water, self.total_unlocked())

    def release(self, op_id: int, nbytes: int = None) -> None:
        with self._lock:
            if nbytes is None:
                self._used.pop(op_id, None)
            else:
                self._used[op_id] = max(
                    0, self._used.get(op_id, 0) - nbytes
                )

    def total_unlocked(self) -> int:
        return sum(self._used.values())

    def total_used(self) -> int:
        with self._lock:
            return self.total_unlocked()

    def headroom(self) -> int:
        return max(0, self.budget - self.total_used())


def batch_device_bytes(cb) -> int:
    """Bytes a ColumnBatch holds resident on device (values + validity)."""
    total = 0
    for c in cb.columns:
        v = c.values
        total += int(getattr(v, "nbytes", 0) or 0)
        if c.validity is not None:
            total += int(getattr(c.validity, "nbytes", 0) or 0)
    return total


def choose_external_bucket_count(est_bytes: int, config=None,
                                 headroom: int = None) -> int:
    """Bucket count for grace (external) execution such that one bucket's
    materialization fits comfortably in the CURRENT device headroom
    (budget minus what other live operators have tracked): each bucket
    gets at most a quarter of it. Grows in powers of two from the
    configured floor (capped at 1024 buckets - past that, per-bucket
    file overhead dominates)."""
    cfg = config or get_config()
    if headroom is None:
        headroom = get_device_tracker().headroom()
    per_bucket = max(1, int(headroom * cfg.memory_fraction) // 4)
    n = max(2, cfg.external_buckets)
    import math

    need = max(1, math.ceil(est_bytes / per_bucket))
    while n < need and n < 1024:
        n *= 2
    return n


_POOL = None
_DEVICE_TRACKER = None


def get_pool() -> MemoryPool:
    global _POOL
    if _POOL is None:
        _POOL = MemoryPool()
    return _POOL


def get_device_tracker() -> DeviceMemoryTracker:
    global _DEVICE_TRACKER
    if _DEVICE_TRACKER is None:
        _DEVICE_TRACKER = DeviceMemoryTracker()
    return _DEVICE_TRACKER
