"""Memory budget + spill ladder.

Reference counterpart: DataFusion's MemoryConsumer/try_grow/spill protocol
wired through MemoryManagerConfig {max_memory, memory_fraction}
(exec.rs:79-94; spill path shuffle_writer_exec.rs:570-623). The TPU engine
extends the ladder one level: device HBM -> host RAM -> disk (SURVEY 7
"spill & memory ladder") - operators materialize on device, overflow to
host buffers tracked here, and spill those to disk under pressure.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List

from blaze_tpu.config import get_config


class MemoryPool:
    """Tracks host-side buffered bytes; triggers consumer spills when the
    budget (max_memory * memory_fraction) is exceeded. Spill order is
    largest-consumer-first like DataFusion's."""

    def __init__(self, budget: int = None):
        cfg = get_config()
        self.budget = budget if budget is not None else int(
            cfg.max_memory * cfg.memory_fraction
        )
        self._used: Dict[int, int] = {}
        self._spill_fns: Dict[int, Callable[[], int]] = {}
        self._lock = threading.Lock()
        self.spill_count = 0
        self.spilled_bytes = 0

    def register(self, consumer_id: int, spill: Callable[[], int]) -> None:
        with self._lock:
            self._used.setdefault(consumer_id, 0)
            self._spill_fns[consumer_id] = spill

    def unregister(self, consumer_id: int) -> None:
        with self._lock:
            self._used.pop(consumer_id, None)
            self._spill_fns.pop(consumer_id, None)

    def total_used(self) -> int:
        with self._lock:
            return sum(self._used.values())

    def grow(self, consumer_id: int, nbytes: int) -> None:
        """Account nbytes to the consumer; spill others (or it) if needed."""
        with self._lock:
            self._used[consumer_id] = self._used.get(consumer_id, 0) + nbytes
            over = sum(self._used.values()) - self.budget
            victims: List[int] = []
            if over > 0:
                victims = sorted(
                    self._used, key=lambda c: -self._used[c]
                )
        if over > 0:
            freed = 0
            for v in victims:
                fn = self._spill_fns.get(v)
                if fn is None:
                    continue
                released = fn()
                with self._lock:
                    self._used[v] = max(0, self._used[v] - released)
                self.spill_count += 1
                self.spilled_bytes += released
                freed += released
                if freed >= over:
                    break

    def shrink(self, consumer_id: int, nbytes: int) -> None:
        with self._lock:
            self._used[consumer_id] = max(
                0, self._used.get(consumer_id, 0) - nbytes
            )


_POOL = None


def get_pool() -> MemoryPool:
    global _POOL
    if _POOL is None:
        _POOL = MemoryPool()
    return _POOL
