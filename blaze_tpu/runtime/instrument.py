"""Per-operator metric instrumentation.

The reference walks the finished plan in lockstep with a mirrored
MetricNode tree and reports per-operator counters into the Spark UI
(metrics.rs:32-56, NativeSupports.scala:215-228). `instrument(op, metrics)`
builds the same mirrored tree over our operator DAG: every node's batch
stream is wrapped to count rows/batches and inclusive elapsed wall time
(an operator's time contains its children's, like a profiler call tree;
subtract child nodes for exclusive time)."""

from __future__ import annotations

import time
from typing import Iterator

from blaze_tpu.batch import ColumnBatch
from blaze_tpu.ops.base import ExecContext, MetricNode, PhysicalOp


class _Instrumented(PhysicalOp):
    def __init__(self, inner: PhysicalOp, node: MetricNode):
        self.inner = inner
        self.node = node
        self.children = inner.children  # already-wrapped children

    @property
    def schema(self):
        return self.inner.schema

    @property
    def partition_count(self):
        return self.inner.partition_count

    def describe(self):
        return self.inner.describe()

    def fingerprint(self):
        return self.inner.fingerprint()

    def execute(self, partition: int, ctx: ExecContext
                ) -> Iterator[ColumnBatch]:
        it = self.inner.execute(partition, ctx)
        while True:
            t0 = time.perf_counter_ns()
            try:
                b = next(it)
            except StopIteration:
                self.node.add(
                    "elapsed_compute", time.perf_counter_ns() - t0
                )
                return
            self.node.add("elapsed_compute", time.perf_counter_ns() - t0)
            self.node.add("output_rows", b.num_rows)
            self.node.add("output_batches", 1)
            yield b

    def __getattr__(self, name):
        # delegate operator-specific attributes (keys, exprs, ...)
        return getattr(self.inner, name)


def instrument(op: PhysicalOp, metrics: MetricNode) -> PhysicalOp:
    """Wrap every node of the plan with a mirrored metric tree."""
    if isinstance(op, _Instrumented):
        return op
    node = MetricNode(op.describe())
    metrics.children.append(node)
    wrapped_children = [instrument(c, node) for c in op.children]
    op.children = wrapped_children
    return _Instrumented(op, node)


def exclusive_elapsed(node: MetricNode) -> int:
    """Exclusive compute nanoseconds for one metric node: inclusive time
    minus the children's inclusive times (clamped at zero - children
    driven from a sibling partition can exceed the parent's window)."""
    own = node.counters.get("elapsed_compute", 0)
    kids = sum(
        c.counters.get("elapsed_compute", 0) for c in node.children
    )
    return max(0, own - kids)


def operator_summary(root: MetricNode, limit: int = 6) -> list:
    """The metric tree flattened to its hottest operators (by
    EXCLUSIVE time): the machine-readable rollup the structured
    slow-query log (obs/slowlog.py) embeds, where the full
    render_metrics tree would bloat a one-line log record."""
    rows = []

    def walk(node: MetricNode) -> None:
        self_ms = exclusive_elapsed(node) / 1e6
        if node.counters:
            rows.append({
                "op": node.name,
                "self_ms": round(self_ms, 3),
                "rows": node.counters.get("output_rows", 0),
            })
        for ch in node.children:
            walk(ch)

    for ch in root.children:
        walk(ch)
    rows.sort(key=lambda r: -r["self_ms"])
    return rows[:max(0, limit)]


def render_metrics(root: MetricNode, indent: str = "") -> str:
    """Spark-UI-style rendering of the mirrored metric tree: one line
    per operator with rows/batches and inclusive + EXCLUSIVE time
    (reference counterpart: the SQLMetric panel fed by metrics.rs).
    Root-level counters - per-task dispatch/transfer/kernel-cache
    accounting recorded by the executor (`dispatch.*`: dispatches,
    h2d_batches, d2h_fetches, kernel_builds vs kernel_hits) - render
    first: dispatch count IS the perf model (runtime/dispatch.py), so
    it belongs in the same report as operator times."""
    lines = []
    if root.counters:
        stats = ", ".join(
            f"{k}={v}" for k, v in sorted(root.counters.items())
        )
        lines.append(f"[task: {stats}]")

    def walk(node: MetricNode, depth: int) -> None:
        c = node.counters
        incl_ms = c.get("elapsed_compute", 0) / 1e6
        excl_ms = exclusive_elapsed(node) / 1e6
        stats = []
        if "output_rows" in c:
            stats.append(f"rows={c['output_rows']:,}")
        if "output_batches" in c:
            stats.append(f"batches={c['output_batches']}")
        stats.append(f"time={incl_ms:.1f}ms")
        stats.append(f"self={excl_ms:.1f}ms")
        for k, v in sorted(c.items()):
            if k not in (
                "output_rows", "output_batches", "elapsed_compute"
            ):
                stats.append(f"{k}={v}")
        lines.append(
            f"{'  ' * depth}{node.name}  [{', '.join(stats)}]"
        )
        for ch in node.children:
            walk(ch, depth + 1)

    for ch in root.children:
        walk(ch, 0)
    return indent + ("\n" + indent).join(lines) if lines else ""
