"""Block transport: the network tier behind CHANNEL shuffle reads.

Reference counterpart: Spark's netty block transfer feeding the native
reader's ReadableByteChannel path (ArrowBlockStoreShuffleReader301.scala:
83-123 recovers local FileSegments for zero-copy reads and hands REMOTE
blocks over as streams; ipc_reader_exec.rs:283-326 wraps the channel).
Here: every worker runs a BlockServer over TCP serving byte ranges of
files under its local data roots; reduce tasks on other hosts stream
remote segments through `open_remote_stream`, which presents a file-like
object the existing segmented-IPC channel decoder consumes unchanged.

Framing (one request per connection, like a shuffle block fetch):
  request:  u32 path_len | path utf8 | i64 offset | i64 length
  response: u8 status (0 ok) | i64 payload_len | payload bytes
"""

from __future__ import annotations

import dataclasses
import io
import os
import socket
import socketserver
import struct
import threading
from typing import List, Optional, Sequence


_REQ_HEAD = struct.Struct("<I")
_REQ_RANGE = struct.Struct("<qq")
_RESP_HEAD = struct.Struct("<Bq")

MAX_PATH = 4096


@dataclasses.dataclass(frozen=True)
class RemoteSegment:
    """A shuffle block living on another host's BlockServer."""

    host: str
    port: int
    path: str
    offset: int
    length: int


_CHUNK = 1 << 20


class BlockProtocolError(IOError):
    """Server answered with an error status - deterministic (bad path,
    scoping violation), so callers must NOT retry it."""


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        server: BlockServer = self.server.block_server  # type: ignore
        try:
            head = _recv_exact(self.request, _REQ_HEAD.size)
            (path_len,) = _REQ_HEAD.unpack(head)
            if path_len > MAX_PATH:
                raise ValueError("path too long")
            path = _recv_exact(self.request, path_len).decode("utf-8")
            offset, length = _REQ_RANGE.unpack(
                _recv_exact(self.request, _REQ_RANGE.size)
            )
            if offset < 0:  # stat request: size in the length field,
                # no payload (offset is never negative for reads)
                size = server.stat(path)
                self.request.sendall(_RESP_HEAD.pack(0, size))
                return
            f, total = server.open_range(path, offset, length)
        except Exception:
            try:
                self.request.sendall(_RESP_HEAD.pack(1, 0))
            except OSError:
                pass
            return
        # stream straight off the file in bounded chunks: O(chunk)
        # memory per connection regardless of block size
        with f:
            self.request.sendall(_RESP_HEAD.pack(0, total))
            left = total
            while left:
                chunk = f.read(min(left, _CHUNK))
                if not chunk:
                    break  # truncated on disk; client sees short stream
                self.request.sendall(chunk)
                left -= len(chunk)


class BlockServer:
    """Serves byte ranges of files under the registered roots (a
    worker's local shuffle/data directories - nothing else is readable,
    mirroring the block-manager's scoping)."""

    def __init__(self, roots: Sequence[str], host: str = "127.0.0.1"):
        self.roots = [os.path.realpath(r) for r in roots]
        self._srv = socketserver.ThreadingTCPServer(
            (host, 0), _Handler, bind_and_activate=True
        )
        self._srv.daemon_threads = True
        self._srv.block_server = self  # type: ignore[attr-defined]
        self._thread = threading.Thread(
            target=self._srv.serve_forever, daemon=True
        )

    @property
    def address(self):
        return self._srv.server_address  # (host, port)

    def start(self) -> "BlockServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()

    def open_range(self, path: str, offset: int, length: int):
        """(open file positioned at offset, byte count) for a scoped
        range; length < 0 means to end-of-file."""
        real = os.path.realpath(path)
        if not any(
            real == r or real.startswith(r + os.sep) for r in self.roots
        ):
            raise PermissionError(f"{path} outside served roots")
        size = os.path.getsize(real)
        if length < 0:
            length = max(size - offset, 0)
        length = min(length, max(size - offset, 0))
        f = open(real, "rb")
        f.seek(offset)
        return f, length

    def read_range(self, path: str, offset: int, length: int) -> bytes:
        f, total = self.open_range(path, offset, length)
        with f:
            return f.read(total)

    def stat(self, path: str) -> int:
        real = os.path.realpath(path)
        if not any(
            real == r or real.startswith(r + os.sep) for r in self.roots
        ):
            raise PermissionError(f"{path} outside served roots")
        return os.path.getsize(real)


class _SocketStream(io.RawIOBase):
    """File-like over the response payload; feeds decode_ipc_stream the
    way the reference wraps a ReadableByteChannel in Read."""

    def __init__(self, sock: socket.socket, remaining: int):
        self._sock = sock
        self._remaining = remaining

    def readable(self) -> bool:
        return True

    def read(self, n: int = -1) -> bytes:
        if self._remaining == 0:
            return b""
        if n is None or n < 0:
            n = self._remaining
        n = min(n, self._remaining)
        chunks = []
        while n:
            b = self._sock.recv(min(n, 1 << 20))
            if not b:
                raise ConnectionError("block stream truncated")
            chunks.append(b)
            n -= len(b)
            self._remaining -= len(b)
        return b"".join(chunks)

    def close(self) -> None:
        try:
            self._sock.close()
        finally:
            super().close()


def open_remote_stream(seg: RemoteSegment,
                       timeout: float = 60.0) -> _SocketStream:
    """Fetch one remote block as a stream (the CHANNEL read path)."""
    sock = socket.create_connection((seg.host, seg.port), timeout=timeout)
    try:
        p = seg.path.encode("utf-8")
        sock.sendall(
            _REQ_HEAD.pack(len(p)) + p
            + _REQ_RANGE.pack(seg.offset, seg.length)
        )
        head = _recv_exact(sock, _RESP_HEAD.size)
        status, length = _RESP_HEAD.unpack(head)
        if status != 0:
            raise BlockProtocolError(
                f"block fetch failed: {seg.path}@{seg.offset}"
            )
        return _SocketStream(sock, length)
    except Exception:
        sock.close()
        raise


def remote_stat(host: str, port: int, path: str,
                timeout: float = 60.0) -> int:
    """File size over the block protocol (offset=-1 stat request)."""
    sock = socket.create_connection((host, port), timeout=timeout)
    try:
        p = path.encode("utf-8")
        sock.sendall(_REQ_HEAD.pack(len(p)) + p + _REQ_RANGE.pack(-1, 0))
        status, size = _RESP_HEAD.unpack(
            _recv_exact(sock, _RESP_HEAD.size)
        )
        if status != 0:
            raise BlockProtocolError(f"stat failed: {path}")
        return size
    finally:
        sock.close()


def iter_remote_batches(seg: RemoteSegment):
    """Stream one remote block's Arrow RecordBatches, closing the socket
    even when the consumer stops early - the single fetch loop shared by
    every remote-read call site."""
    from blaze_tpu.io.ipc import decode_ipc_stream

    stream = open_remote_stream(seg)
    try:
        yield from decode_ipc_stream(stream)
    finally:
        stream.close()


def _recv_exact(sock, n: int) -> bytes:
    # recv_into a preallocated buffer: large frames (multi-MB result
    # parts) would otherwise pay O(n^2) bytes-concat churn
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        k = sock.recv_into(view[got:], n - got)
        if not k:
            raise ConnectionError("socket closed mid-frame")
        got += k
    return bytes(buf)


# sendmsg iovec bound: Linux caps a single sendmsg at IOV_MAX (1024)
# buffers; stay comfortably under it
_IOV_CHUNK = 512


def sendmsg_all(sock, buffers) -> int:
    """Scatter-gather send: write a buffer list (bytes / memoryview,
    e.g. the arena's mmap-backed frame views) without concatenating a
    reply - the writev-style half of the zero-copy serve path. Handles
    partial sends and IOV_MAX chunking; falls back to sendall when the
    socket has no sendmsg (test doubles). Returns bytes sent."""
    views = [memoryview(b) for b in buffers if len(b)]
    sendmsg = getattr(sock, "sendmsg", None)
    total = 0
    if sendmsg is None:
        for v in views:
            sock.sendall(v)
            total += len(v)
        return total
    while views:
        try:
            sent = sendmsg(views[:_IOV_CHUNK])
        except InterruptedError:
            continue
        total += sent
        while sent and views:
            head = views[0]
            if sent >= len(head):
                sent -= len(head)
                views.pop(0)
            else:
                views[0] = head[sent:]
                sent = 0
    return total
