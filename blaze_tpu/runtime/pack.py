"""Packed host<->device transfers: O(1) round trips per batch.

Why this exists: every `jax.Array` leaf in a `device_get` and every
`device_put` pays its own host<->device round trip. On a network-attached
TPU each round trip is tens of milliseconds, so a 20-column batch costs
20x the latency of a 1-column batch even when the bytes are tiny. The
reference hands a whole batch across its FFI boundary as ONE pointer
pair per batch (exec.rs:205-255); the TPU-native equivalent is to pack
all of a batch's buffers into ONE uint8 buffer on one side and split it
on the other:

- D2H (`get_packed`): a cached jit kernel slices each buffer to the live
  prefix, bitcasts to bytes and concatenates -> one fetch -> host views
  split it back (zero-copy numpy views into the fetched buffer).
- H2D (`put_packed`): host concatenates raw bytes -> one device_put ->
  a cached jit kernel splits and bitcasts back to typed device arrays.

Byte order: XLA's bitcast-convert to/from uint8 enumerates bytes in
little-endian element order on all supported backends, matching numpy's
`.view` on little-endian hosts; `tests/test_pack.py` round-trips every
engine dtype to pin this.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from blaze_tpu.runtime.dispatch import cached_kernel, record
from blaze_tpu.obs import trace as obs_trace
from blaze_tpu.testing import chaos


def _np_dtype(a) -> np.dtype:
    return np.dtype(a.dtype)


def _packed_nbytes(shape: Tuple[int, ...], dt: np.dtype) -> int:
    n = int(np.prod(shape)) if shape else 1
    return n * (1 if dt == np.bool_ else dt.itemsize)


def _f64_pairs() -> bool:
    """True when float64 must travel as exact (hi, lo) float32 pairs.

    The TPU backend has no hardware f64: XLA emulates it as a
    double-single (two-float32) pair with an f32 exponent range, and the
    axon AOT compiler's X64-removal pass cannot lower bitcast-convert on
    f64 at all. hi = f32(x), lo = f32(x - hi) is the exact double-single
    decomposition - it round-trips every value the device itself can
    represent, using only arithmetic + f32 bitcasts. CPU (true IEEE f64)
    keeps the direct byte bitcast, which is lossless there."""
    return jax.default_backend() != "cpu"


def _build_pack(slice_rows: Optional[int], f64_pairs: bool):
    """Device kernel: [arrays] -> one uint8 buffer. Shapes/dtypes are
    picked up from the traced inputs; jax.jit specializes per signature
    under the single cache entry."""

    def pack(bufs):
        parts = []
        for b in bufs:
            if slice_rows is not None and b.ndim >= 1:
                b = b[:slice_rows]
            if b.dtype == jnp.bool_:
                b = b.astype(jnp.uint8)
            if f64_pairs and b.dtype == jnp.float64:
                hi = b.astype(jnp.float32)
                lo = (b - hi.astype(jnp.float64)).astype(jnp.float32)
                lo = jnp.where(jnp.isfinite(hi), lo, jnp.float32(0))
                b = jnp.stack([hi, lo], axis=-1)
            b = b.reshape(-1)
            if b.dtype != jnp.uint8:
                b = jax.lax.bitcast_convert_type(b, jnp.uint8)
                b = b.reshape(-1)
            parts.append(b)
        if not parts:
            return jnp.zeros(0, dtype=jnp.uint8)
        return jnp.concatenate(parts)

    return pack


def _build_unpack(metas: Tuple[Tuple[str, Tuple[int, ...]], ...],
                  f64_pairs: bool):
    """Device kernel: one uint8 buffer -> [typed arrays] per metas
    (contiguous layout: the `concatenate`d put_packed wire format)."""
    at = []
    off = 0
    for dt_s, shape in metas:
        nb = _packed_nbytes(shape, np.dtype(dt_s))
        at.append((dt_s, shape, off, nb))
        off += nb
    return _build_unpack_at(tuple(at), f64_pairs)


def _f64_to_pair_bytes(a: np.ndarray) -> np.ndarray:
    """Host-side exact double-single split, little-endian f32-pair bytes."""
    hi = a.astype(np.float32)
    with np.errstate(invalid="ignore"):
        lo = (a - hi.astype(np.float64)).astype(np.float32)
    lo = np.where(np.isfinite(hi), lo, np.float32(0))
    pair = np.empty(a.shape + (2,), dtype=np.float32)
    pair[..., 0] = hi
    pair[..., 1] = lo
    return pair.reshape(-1).view(np.uint8)


def _pair_bytes_to_f64(seg: np.ndarray, n: int) -> np.ndarray:
    pair = seg.view(np.float32).reshape(n, 2)
    hi = pair[:, 0].astype(np.float64)
    lo = pair[:, 1].astype(np.float64)
    return np.where(pair[:, 1] == 0, hi, hi + lo)


def put_packed(arrays: Sequence[np.ndarray]) -> List[jax.Array]:
    """Move host arrays to device in ONE transfer + ONE split dispatch."""
    if not arrays:
        return []
    if chaos.ACTIVE:
        # chaos seam: the host->device staging transfer fails (a
        # network-attached device drops the RPC)
        chaos.fire("h2d.transfer", n_arrays=len(arrays))
    if obs_trace.ACTIVE:
        # obs seam: the H2D staging transfer as one span (pack +
        # device_put + unpack-kernel launch); no-op without a
        # thread-current recorder
        with obs_trace.span("h2d", n_arrays=len(arrays)):
            return _put_packed(arrays)
    return _put_packed(arrays)


def _put_packed(arrays: Sequence[np.ndarray]) -> List[jax.Array]:
    pairs = _f64_pairs()
    metas = tuple((str(_np_dtype(a)), tuple(a.shape)) for a in arrays)
    parts = []
    for a in arrays:
        a = np.ascontiguousarray(a)
        if a.dtype == np.bool_:
            a = a.astype(np.uint8)
        if pairs and a.dtype == np.float64:
            parts.append(_f64_to_pair_bytes(a))
            continue
        parts.append(a.reshape(-1).view(np.uint8))
    buf = np.concatenate(parts) if parts else np.zeros(0, np.uint8)
    record("h2d_batches")
    dev = jax.device_put(buf)
    fn = cached_kernel(
        ("h2d_unpack", metas, pairs),
        lambda: _build_unpack(metas, pairs),
    )
    return list(fn(dev))


_ALIGN = 16  # segment alignment so host typed views into the buffer work


def _aligned_metas(entries):
    """[(dtype_str, full_shape, off, nb)] with aligned offsets + total."""
    metas = []
    off = 0
    for vals, cap, _fill in entries:
        tail = tuple(vals.shape[1:])
        dt = np.dtype(vals.dtype)
        nb = _packed_nbytes((cap,) + tail, dt)
        metas.append((str(dt), (cap,) + tail, off, nb))
        off += (nb + _ALIGN - 1) // _ALIGN * _ALIGN
    return tuple(metas), off


def _build_unpack_at(metas, f64_pairs: bool):
    """Device kernel: one uint8 buffer -> typed arrays at given offsets."""

    def unpack(u8):
        outs = []
        for dt_s, shape, off, nb in metas:
            dt = np.dtype(dt_s)
            n = int(np.prod(shape)) if shape else 1
            seg = jax.lax.slice(u8, (off,), (off + nb,))
            if dt == np.bool_:
                arr = seg.astype(jnp.bool_)
            elif f64_pairs and dt == np.float64:
                pair = jax.lax.bitcast_convert_type(
                    seg.reshape(2 * n, 4), jnp.float32
                ).reshape(n, 2)
                hi = pair[:, 0].astype(jnp.float64)
                lo = pair[:, 1].astype(jnp.float64)
                arr = jnp.where(pair[:, 1] == 0, hi, hi + lo)
            elif dt.itemsize == 1:
                arr = jax.lax.bitcast_convert_type(seg, jnp.dtype(dt))
            else:
                arr = jax.lax.bitcast_convert_type(
                    seg.reshape(n, dt.itemsize), jnp.dtype(dt)
                )
            outs.append(arr.reshape(shape))
        return outs

    return unpack


def put_packed_padded(entries: Sequence[Tuple[np.ndarray, int, int]]
                      ) -> List[jax.Array]:
    """Pad + pack + transfer in ONE host copy and ONE device round trip.

    Each entry is `(vals, cap, fill)`: a host array whose leading axis has
    n live rows, the padded capacity, and the scalar tail-fill value. The
    returned device arrays have shape `(cap,) + vals.shape[1:]`. This
    fuses the shape-bucket padding copy (previously a separate
    `np.zeros(cap); padded[:n] = vals` per column) with the transfer
    packing copy - the padded column is written directly into its
    aligned segment of the single wire buffer."""
    dev, metas, pairs = put_packed_padded_lazy(entries)
    if dev is None:
        return []
    fn = cached_kernel(
        ("h2d_unpack_at", metas, pairs),
        lambda: _build_unpack_at(metas, pairs),
    )
    return list(fn(dev))


def put_packed_padded_lazy(
    entries: Sequence[Tuple[np.ndarray, int, int]]
) -> Tuple[Optional[jax.Array], Tuple, bool]:
    """Pad + pack + transfer WITHOUT the unpack dispatch.

    Returns `(device_u8_buffer, metas, f64_pairs)`; the caller either
    splits the buffer later with `unpack_kernel(metas, pairs)` (one
    dispatch, the classic path) or - the pipeline-fusion fast path -
    composes `build_unpack_at(metas, pairs)` into its OWN jitted kernel
    so transfer-unpacking and the consuming operator chain cost a single
    dispatch total (batch.PackedColumnBatch owns that deferral)."""
    if not entries:
        return None, (), _f64_pairs()
    pairs = _f64_pairs()
    norm = []
    for vals, cap, fill in entries:
        vals = np.asarray(vals)
        norm.append((vals, cap, fill))
    metas, total = _aligned_metas(norm)
    buf = np.empty(total, dtype=np.uint8)
    for (vals, cap, fill), (dt_s, shape, off, nb) in zip(norm, metas):
        n = vals.shape[0] if vals.ndim else 0
        dt = np.dtype(dt_s)
        seg = buf[off: off + nb]
        if dt == np.bool_:
            view = seg.reshape(shape)
            view[:n] = vals.astype(np.uint8).reshape(vals.shape)
            view[n:] = 1 if fill else 0
        elif pairs and dt == np.float64:
            # the pair tail encodes only 0.0; a nonzero fill would be
            # silently wrong, so enforce the contract (ValueError, not
            # assert: must survive python -O)
            if fill:
                raise ValueError(
                    "f64-pair padding supports fill=0 only (got "
                    f"{fill!r})"
                )
            pb = _f64_to_pair_bytes(np.ascontiguousarray(vals))
            seg[: pb.size] = pb
            seg[pb.size:] = 0
        else:
            view = seg.view(dt).reshape(shape)
            view[:n] = vals
            view[n:] = fill
    record("h2d_batches")
    dev = jax.device_put(buf)
    return dev, metas, pairs


def unpack_kernel(metas, pairs: bool):
    """The cached one-dispatch splitter for a lazily packed buffer (same
    cache key as the classic put_packed_padded path, so both share one
    compiled executable per layout)."""
    return cached_kernel(
        ("h2d_unpack_at", metas, pairs),
        lambda: _build_unpack_at(metas, pairs),
    )


def build_unpack_at(metas, pairs: bool):
    """Traceable u8-buffer splitter for composing into a larger jitted
    kernel (pipeline fusion: unpack + operator chain = one program)."""
    return _build_unpack_at(metas, pairs)


def get_packed(arrays: Sequence[object],
               slice_rows: Optional[int] = None) -> List[np.ndarray]:
    """Fetch a mixed list of jax/numpy arrays in ONE device round trip.

    numpy entries pass through untouched. `slice_rows` statically caps the
    FIRST axis of every device array with ndim>=1 before the transfer (the
    caller knows live rows << capacity); the returned host arrays reflect
    the capped shapes."""
    out: List[object] = list(arrays)
    dev_idx = [
        i for i, a in enumerate(arrays)
        if isinstance(a, jax.Array)
    ]
    if not dev_idx:
        return out  # type: ignore[return-value]
    pairs = _f64_pairs()
    fn = cached_kernel(
        ("d2h_pack", slice_rows, pairs),
        lambda: _build_pack(slice_rows, pairs),
    )
    packed = fn([arrays[i] for i in dev_idx])
    record("d2h_fetches")
    host = np.asarray(packed)
    off = 0
    for i in dev_idx:
        a = arrays[i]
        shape = tuple(a.shape)
        if slice_rows is not None and len(shape) >= 1:
            shape = (min(slice_rows, shape[0]),) + shape[1:]
        dt = _np_dtype(a)
        nb = _packed_nbytes(shape, dt)
        seg = host[off: off + nb]
        if dt == np.bool_:
            vals = seg.view(np.bool_)
        elif pairs and dt == np.float64:
            n = int(np.prod(shape)) if shape else 1
            vals = _pair_bytes_to_f64(seg, n)
        else:
            vals = seg.view(dt)
        out[i] = vals.reshape(shape)
        off += nb
    return out  # type: ignore[return-value]


def pack_in_kernel(arrays: Sequence[jax.Array]) -> jax.Array:
    """Traceable packer: concatenate typed device arrays into one uint8
    buffer INSIDE an enclosing jitted kernel (f64 travels as exact
    double-single pairs off-CPU, mirroring `_build_pack`). Pair with
    `unpack_host` so a kernel's small auxiliary outputs (streaming
    aggregate carry states) reach the host in one fetch with no extra
    pack dispatch."""
    return _build_pack(None, _f64_pairs())(list(arrays))


def unpack_host(host_u8: np.ndarray,
                specs: Sequence[Tuple[str, Tuple[int, ...]]]
                ) -> List[np.ndarray]:
    """Split a host copy of a `pack_in_kernel` buffer back into typed
    arrays per `(dtype_str, shape)` specs (the wire format of
    `_build_pack`: contiguous, unaligned, bool as u8, f64 as f32 pairs
    off-CPU)."""
    pairs = _f64_pairs()
    out: List[np.ndarray] = []
    off = 0
    for dt_s, shape in specs:
        dt = np.dtype(dt_s)
        n = int(np.prod(shape)) if shape else 1
        nb = n * (1 if dt == np.bool_ else dt.itemsize)
        if pairs and dt == np.float64:
            nb = n * 8  # two f32 per element
        seg = host_u8[off: off + nb]
        if dt == np.bool_:
            vals = seg.view(np.uint8).astype(np.bool_)
        elif pairs and dt == np.float64:
            vals = _pair_bytes_to_f64(seg, n)
        else:
            vals = seg.view(dt)
        out.append(vals.reshape(shape))
        off += nb
    return out
