"""Task gateway: the engine's cross-language entry point over a socket.

The reference's defining boundary is JNI + Arrow C-Data with a batch
handshake (exec.rs:118-255 decodes a TaskDefinition from the JVM and
pumps batches back; JniBridge.java:33-36). This environment has no JVM,
so the exercised out-of-process embedding is a socket gateway speaking
the same two currencies: TaskDefinition protobuf in, segmented Arrow-IPC
parts out (the u64-LE length + zstd Arrow-IPC framing of io/ipc.py -
also the shuffle wire format, so any client that reads shuffle files can
read this). A C++ client (cpp/blaze_client.cpp) drives it in tests,
proving the L4 gateway contract without Python on the embedder side.

Framing:
  request:  u64-LE header | [manifest] | TaskDefinition protobuf bytes
            header low 62 bits = blob_len; bit 63 set = the blob is in
            the REFERENCE wire format (plan/refcompat.py decodes it -
            the reference's own plan.proto:508-513 TaskDefinition);
            bit 61 set = the connection speaks the multi-query
            SERVICE protocol (service/wire.py verbs: submit / poll /
            fetch-stream / cancel over one connection) - requires a
            QueryService attached (`python -m blaze_tpu serve`);
            bit 62 set = a resource manifest precedes the blob:
            u32-LE json_len | JSON {resource_id: [[source...] per
            partition]}, source = {"file": p, "offset": o, "length": l}
            (shuffle FileSegment) or {"b64": "..."} (raw IPC part
            bytes) - the socket-tier analog of the reference's JVM
            resource registry (JniBridge.java:31).
  response: per batch, one segmented-IPC part (u64-LE part_len | zstd
            Arrow IPC stream)
            then u64-LE 0 (end of stream)
            on error: u64-LE 0xFFFFFFFFFFFFFFFF | u32-LE msg_len | utf8
"""

from __future__ import annotations

import base64
import json
import logging
import os
import socketserver
import struct
import threading
from typing import Optional

from blaze_tpu.runtime.transport import _recv_exact

log = logging.getLogger("blaze_tpu.gateway")

_U64 = struct.Struct("<Q")
_U32 = struct.Struct("<I")
_ERR = 0xFFFFFFFFFFFFFFFF
_FLAG_REF = 1 << 63
_FLAG_MANIFEST = 1 << 62
# connection-mode switch: a first header with this bit set speaks the
# multi-query service protocol (service/wire.py) instead of the legacy
# one-shot task exchange
_FLAG_SERVICE = 1 << 61
MAX_TASK_BYTES = 64 << 20


def _manifest_resources(manifest: dict):
    """Decode a JSON resource manifest into ExecContext providers."""
    from blaze_tpu.ops.ipc_reader import FileSegment

    def src(d):
        if "file" in d:
            return FileSegment(
                d["file"], int(d.get("offset", 0)),
                int(d["length"]),
            )
        if "b64" in d:
            return base64.b64decode(d["b64"])
        raise ValueError(f"unknown manifest source {sorted(d)}")

    return {
        rid: [[src(s) for s in part] for part in parts]
        for rid, parts in manifest.items()
    }


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        sock = self.request
        try:
            (header,) = _U64.unpack(_recv_exact(sock, _U64.size))
        except Exception:
            return
        if header & _FLAG_SERVICE:
            # multi-query service connection (service/wire.py);
            # requires a QueryService attached to the server
            service = getattr(self.server, "service", None)
            if service is None:
                msg = b"no query service attached"
                try:
                    sock.sendall(
                        _U64.pack(_ERR) + _U32.pack(len(msg)) + msg
                    )
                except OSError:
                    pass
                return
            from blaze_tpu.service.wire import (
                handle_service_connection,
            )

            handle_service_connection(sock, service)
            return
        handle_legacy_connection(sock, header)


def handle_legacy_connection(sock, header: int) -> None:
    """One-shot task exchange (the pre-service gateway protocol); the
    hello u64 is already consumed. Shared by the threaded handler
    above and the event-loop plane (service/wire_async.py), which
    hands legacy connections to a daemon thread - task execution is
    blocking, thread-shaped work."""
    from blaze_tpu.io.ipc import encode_ipc_segment
    from blaze_tpu.runtime.executor import ExecContext, execute_task

    try:
        is_ref = bool(header & _FLAG_REF)
        has_manifest = bool(header & _FLAG_MANIFEST)
        blob_len = header & ~(
            _FLAG_REF | _FLAG_MANIFEST | _FLAG_SERVICE
        )
        if blob_len > MAX_TASK_BYTES:
            raise ValueError("task too large")
        manifest_raw = None
        if has_manifest:
            (mlen,) = _U32.unpack(_recv_exact(sock, _U32.size))
            if mlen > MAX_TASK_BYTES:
                raise ValueError("manifest too large")
            manifest_raw = _recv_exact(sock, mlen)
        blob = _recv_exact(sock, blob_len)
    except Exception:
        return
    batches = None
    try:
        # manifest SEMANTIC failures (bad JSON, missing keys) get
        # the documented error frame - only framing failures above
        # drop the connection
        resources = (
            _manifest_resources(json.loads(manifest_raw))
            if manifest_raw is not None else {}
        )
        ctx = ExecContext()
        ctx.resources.update(resources)
        if is_ref:
            from blaze_tpu.plan.refcompat import (
                execute_reference_task,
            )

            batches = execute_reference_task(blob, ctx=ctx)
        else:
            batches = execute_task(blob, ctx=ctx)
        it = iter(batches)
        while True:
            rb = next(it, None)  # execution errors surface here
            if rb is None:
                break
            part = encode_ipc_segment(rb)
            try:
                sock.sendall(part)  # already u64-LE length-prefixed
            except OSError:
                # client hung up mid-stream: this is a CANCELLATION,
                # not an execution failure (the executor's
                # GeneratorExit pass-through, executor.py) - close
                # the task generator so operators unwind cleanly
                # and keep the engine unpoisoned; no error frame,
                # no task-failure logging
                it.close()
                log.info(
                    "client disconnected mid-stream; task cancelled"
                )
                return
        sock.sendall(_U64.pack(0))
    except Exception as e:
        msg = str(e).encode("utf-8")[:65536]
        try:
            sock.sendall(_U64.pack(_ERR) + _U32.pack(len(msg)) + msg)
        except OSError:
            pass
    finally:
        if batches is not None:
            close = getattr(batches, "close", None)
            if close is not None:
                close()


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True  # fixed-port restarts during TIME_WAIT


class TaskGatewayServer:
    """Gateway listener. `wire` picks the data plane: "async" (the
    default; event-loop verb serving, service/wire_async.py) or
    "threaded" (the legacy thread-per-connection socketserver, kept as
    the differential oracle for wire-parity tests). BLAZE_WIRE
    overrides the default for whole-process flips."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 service=None, wire: Optional[str] = None):
        if wire is None:
            wire = os.environ.get("BLAZE_WIRE", "async")
        if wire not in ("async", "threaded"):
            raise ValueError(f"unknown wire mode {wire!r}")
        self.wire = wire
        self.service = service
        self._srv = None
        self._async = None
        self._thread = None
        if wire == "threaded":
            self._srv = _Server(
                (host, port), _Handler, bind_and_activate=True
            )
            self._srv.daemon_threads = True
            # optional QueryService: enables service-protocol
            # connections (_FLAG_SERVICE) on the same listener
            self._srv.service = service
            self._thread = threading.Thread(
                target=self._srv.serve_forever, daemon=True
            )
        else:
            from blaze_tpu.service import wire_async

            self._async = wire_async.AsyncWireServer(
                host, port, self._handle_async
            )

    async def _handle_async(self, conn):
        from blaze_tpu.service import wire_async
        from blaze_tpu.service.wire import ServiceVerbBackend

        service = self.service
        await wire_async.handle_wire_connection(
            conn,
            backend_factory=(
                (lambda: ServiceVerbBackend(service))
                if service is not None else None
            ),
            legacy=handle_legacy_connection,
        )

    @property
    def address(self):
        if self._async is not None:
            return self._async.address
        return self._srv.server_address

    def start(self) -> "TaskGatewayServer":
        if self._async is not None:
            self._async.start()
        else:
            self._thread.start()
        return self

    def serve_blocking(self) -> None:
        """Block the calling thread in the accept loop (the CLI
        shape). On the threaded plane this IS the accept loop and is
        mutually exclusive with start(); on the async plane accepting
        always happens on the wire loop and this just parks until
        shutdown(). Returns after shutdown()."""
        if self._async is not None:
            self._async.serve_blocking()
        else:
            self._srv.serve_forever()

    def shutdown(self) -> None:
        """Stop the accept loop (serve_blocking returns / the start()
        thread exits) without closing the listener; safe from any
        thread - the drain path calls it once the service is empty."""
        if self._async is not None:
            self._async.shutdown()
        else:
            self._srv.shutdown()

    def stop(self) -> None:
        if self._async is not None:
            self._async.stop()
        else:
            self._srv.shutdown()
            self._srv.server_close()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


def serve_forever(host: str = "127.0.0.1", port: int = 8484,
                  service=None) -> None:  # pragma: no cover - CLI
    srv = TaskGatewayServer(host, port, service=service)
    print(f"blaze_tpu gateway listening on {srv.address}", flush=True)
    srv.serve_blocking()
