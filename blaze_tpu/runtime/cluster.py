"""Mini-cluster runner: multi-PROCESS stage execution over the file
fabric.

The multi-host story of this engine (SURVEY 2.4): hosts coordinate through
serialized TaskDefinitions and the segmented Arrow-IPC shuffle files -
exactly how a Spark cluster drives the reference (tasks arrive as protobuf
over JNI, shuffle moves as .data/.index files). This runner proves that
path with real process isolation: a driver serializes each map task to a
spool directory, worker PROCESSES (separate interpreters, separate JAX
runtimes - `python -m blaze_tpu worker`) claim tasks by atomic rename,
execute them through `runtime.executor.execute_task`, and write results as
segmented IPC; the driver assembles. No state crosses process boundaries
except protobuf + IPC files, so the same layout scales to real multi-host
DCN with a shared filesystem or object store.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time
import uuid
from typing import List, Optional, Sequence

import pyarrow as pa

from blaze_tpu.obs import trace as obs_trace
from blaze_tpu.ops.base import ExecContext, PhysicalOp
from blaze_tpu.testing import chaos


class Liveness:
    """Progress-aware liveness window - the run_tasks contract (round-5
    flake: a fixed wall-clock deadline killed live-but-slow workers),
    factored out so the replica router's membership registry
    (blaze_tpu/router/registry.py) applies the identical policy to
    STATS-poll heartbeats: any sign of life resets the window, and
    `expired()` is true only when nothing progressed within it -
    "provably dead or wedged", never merely "slow"."""

    def __init__(self, clock=time.time):
        self._clock = clock
        self._last = clock()

    def note_progress(self, at: Optional[float] = None) -> None:
        self._last = max(
            self._last, self._clock() if at is None else at
        )

    def idle_s(self, now: Optional[float] = None) -> float:
        return (self._clock() if now is None else now) - self._last

    def expired(self, timeout: float,
                now: Optional[float] = None) -> bool:
        return self.idle_s(now) > timeout


class MiniCluster:
    """The control plane (task spool) is shared - it plays the driver
    RPC role - but every worker owns a PRIVATE data directory for its
    shuffle outputs, exported only through its BlockServer
    (runtime/transport.py). Remote reads therefore go over the network,
    never through the shared filesystem."""

    def __init__(self, num_workers: int = 2,
                 spool_dir: Optional[str] = None,
                 env: Optional[dict] = None,
                 task_max_attempts: int = 2,
                 quarantine_after: int = 2):
        self.num_workers = num_workers
        self.spool = spool_dir or tempfile.mkdtemp(prefix="blz-cluster-")
        os.makedirs(os.path.join(self.spool, "tasks"), exist_ok=True)
        os.makedirs(os.path.join(self.spool, "claimed"), exist_ok=True)
        os.makedirs(os.path.join(self.spool, "out"), exist_ok=True)
        os.makedirs(os.path.join(self.spool, "quarantine"),
                    exist_ok=True)
        self._procs: List[subprocess.Popen] = []
        self._env = env
        # failure policy (blaze_tpu/errors.py): a TRANSIENT-classified
        # task failure is re-spooled up to task_max_attempts total; a
        # worker that reports quarantine_after FATAL_FOR_WORKER
        # failures (INTERNAL / RESOURCE_EXHAUSTED - the worker itself
        # is suspect) gets a quarantine marker and stops claiming
        self.task_max_attempts = max(1, int(task_max_attempts))
        self.quarantine_after = max(1, int(quarantine_after))
        self._worker_failures: dict = {}
        self.quarantined: List[str] = []

    # ------------------------------------------------------------------
    def start(self) -> None:
        env = dict(os.environ)
        env.update(self._env or {})
        repo = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        for i in range(self.num_workers):
            data_dir = tempfile.mkdtemp(prefix=f"blz-worker{i}-")
            # stderr to a FILE, never a pipe: nothing drains a pipe, so
            # a chatty worker (jax compile-cache warnings scale with
            # kernel count) would fill the 64KB buffer and block
            # forever mid-compile - task timeouts with no .err file
            # were this deadlock
            errlog = open(
                os.path.join(self.spool, f"worker{i}.stderr"), "wb"
            )
            self._procs.append(
                subprocess.Popen(
                    [sys.executable, "-m", "blaze_tpu.runtime.cluster",
                     self.spool, data_dir],
                    env=env,
                    stdout=subprocess.DEVNULL,
                    stderr=errlog,
                )
            )
            errlog.close()  # the child holds its own descriptor

    def stop(self) -> None:
        open(os.path.join(self.spool, "SHUTDOWN"), "w").close()
        for p in self._procs:
            try:
                p.wait(timeout=30)
            except subprocess.TimeoutExpired:
                p.kill()
        self._procs.clear()

    # ------------------------------------------------------------------
    def run_tasks(self, task_blobs: Sequence[bytes],
                  timeout: float = 300.0,
                  return_metas: bool = False,
                  tracer=None):
        """Submit serialized TaskDefinitions; wait for per-task results
        (tables decoded from segmented IPC). With return_metas, also
        return each task's worker-reported metadata (block-server
        address + shuffle output ranges) - per call, so concurrent map
        stages on one cluster can't clobber each other.

        `tracer` (an obs.trace.TraceRecorder; defaults to the calling
        thread's current recorder) receives each worker's serialized
        span subtree - one stitched cross-process trace per run. Spawn
        workers with BLAZE_TRACE=1 in `env` so they record at all.

        Liveness is PROGRESS-AWARE, not a fixed wall-clock deadline (the
        round-5 flake: a fixed deadline killed live tasks whose workers
        were mid-first-compile under round-end load). Each worker
        heartbeats its claimed-task file's mtime while executing
        (_HEARTBEAT_S); `timeout` here bounds INACTIVITY - the run only
        fails once no claimed task has heartbeat within the window and
        no completion arrived, i.e. when the workers are provably dead
        or wedged rather than merely slow."""
        from blaze_tpu.io.ipc import decode_ipc_parts

        if tracer is None and obs_trace.ACTIVE:
            tracer = obs_trace.current_recorder()
        metas: List[Optional[dict]] = [None] * len(task_blobs)
        ids = []
        for blob in task_blobs:
            tid = uuid.uuid4().hex
            tmp = os.path.join(self.spool, "tasks", f".{tid}.tmp")
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, os.path.join(self.spool, "tasks", tid))
            ids.append(tid)
        live = Liveness()
        tables: List[Optional[pa.Table]] = [None] * len(ids)
        pending = set(range(len(ids)))
        attempts = [1] * len(ids)
        claimed_dir = os.path.join(self.spool, "claimed")
        while pending:
            now = time.time()
            # any fresh heartbeat (claimed-file mtime) counts as
            # progress; so does an unclaimed task while some OTHER task
            # is being worked (a busy 1-core worker pool is not a hang)
            for i in pending:
                try:
                    hb = os.path.getmtime(
                        os.path.join(claimed_dir, ids[i])
                    )
                except OSError:
                    continue  # not claimed yet (or just completed)
                live.note_progress(hb)
            if live.expired(timeout, now):
                raise TimeoutError(
                    f"tasks incomplete: {pending} (no worker progress "
                    f"for {live.idle_s(now):.0f}s)"
                )
            if (
                len(self.quarantined) >= self.num_workers
                and all(
                    os.path.exists(
                        os.path.join(self.spool, "tasks", ids[i])
                    )
                    for i in pending
                )
            ):
                # every slot is quarantined and every pending task is
                # sitting unclaimed: nothing can make progress - fail
                # now instead of burning the full inactivity timeout
                raise RuntimeError(
                    f"all {self.num_workers} worker slots quarantined "
                    f"with {len(pending)} tasks unclaimed"
                )
            for i in list(pending):
                done = os.path.join(self.spool, "out", ids[i] + ".done")
                err = os.path.join(self.spool, "out", ids[i] + ".err")
                if os.path.exists(err):
                    with open(err) as f:
                        info = _parse_err(f.read())
                    if tracer is not None and info.get("spans"):
                        # failed attempts keep their spans too - a
                        # retried task renders as two worker subtrees
                        tracer.attach_subtree(info["spans"])
                    # quarantine accounting FIRST, so a wedged worker
                    # stops claiming before the re-spooled task lands
                    # back in the pool (in-run protection, not just
                    # across runs)
                    self._note_worker_failure(info)
                    if (
                        info["class"] != "PLAN_INVALID"
                        and attempts[i] < self.task_max_attempts
                    ):
                        # classified retry: TRANSIENT plausibly clears
                        # on re-run; fatal classes get one shot on a
                        # (possibly different, post-quarantine) worker.
                        # PLAN_INVALID never retries - the task is bad,
                        # not the worker.
                        attempts[i] += 1
                        os.unlink(err)
                        tmp = os.path.join(
                            self.spool, "tasks", f".{ids[i]}.tmp"
                        )
                        with open(tmp, "wb") as f:
                            f.write(task_blobs[i])
                        os.replace(
                            tmp,
                            os.path.join(self.spool, "tasks", ids[i]),
                        )
                        live.note_progress()
                        continue
                    raise RuntimeError(
                        f"worker task failed [{info['class']}]: "
                        f"{info['error']}"
                    )
                if os.path.exists(done):
                    with open(
                        os.path.join(self.spool, "out", ids[i] + ".ipc"),
                        "rb",
                    ) as f:
                        batches = list(decode_ipc_parts(f.read()))
                    tables[i] = (
                        pa.Table.from_batches(batches)
                        if batches else pa.table({})
                    )
                    meta = os.path.join(
                        self.spool, "out", ids[i] + ".meta.json"
                    )
                    if os.path.exists(meta):
                        with open(meta) as f:
                            metas[i] = json.load(f)
                        if tracer is not None and metas[i].get("spans"):
                            tracer.attach_subtree(metas[i]["spans"])
                    pending.discard(i)
                    live.note_progress()
            time.sleep(0.05)
        if return_metas:
            return tables, metas
        return tables  # type: ignore[return-value]

    def _note_worker_failure(self, info: dict) -> None:
        """Count classified-fatal failures per worker; after
        quarantine_after of them the worker slot is quarantined (a
        marker file its claim loop checks) - a wedged runtime must not
        keep eating tasks the way a Spark executor blacklisted after
        repeated task failures would."""
        from blaze_tpu.errors import FATAL_FOR_WORKER, ErrorClass

        wid = info.get("pid")
        if wid is None:
            return
        try:
            fatal = ErrorClass(info["class"]) in FATAL_FOR_WORKER
        except ValueError:
            fatal = True
        if not fatal:
            return
        wid = str(wid)
        self._worker_failures[wid] = (
            self._worker_failures.get(wid, 0) + 1
        )
        if (
            self._worker_failures[wid] >= self.quarantine_after
            and wid not in self.quarantined
        ):
            open(
                os.path.join(self.spool, "quarantine", wid), "w"
            ).close()
            self.quarantined.append(wid)
            # process-wide observability: quarantines surface in the
            # METRICS exposition and the service STATS payload
            from blaze_tpu.obs.metrics import REGISTRY

            REGISTRY.inc("blaze_worker_quarantines_total")

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()


def _parse_err(text: str) -> dict:
    """Decode a worker .err payload. Workers write JSON
    {pid, class, error, traceback}; plain text (older workers, partial
    writes) degrades to an INTERNAL classification."""
    try:
        info = json.loads(text)
        if isinstance(info, dict) and "class" in info:
            info.setdefault("error", "")
            return info
    except (ValueError, TypeError):
        pass
    return {"pid": None, "class": "INTERNAL", "error": text}


# ---------------------------------------------------------------------------
# worker loop (runs in its own interpreter/JAX runtime)
# ---------------------------------------------------------------------------

WORKER_LOCAL_PREFIX = "__WORKER_LOCAL__"

# worker -> driver liveness signal: the claimed-task file's mtime is
# bumped this often while the task executes, so the driver can tell
# "alive but compiling/slow" from "dead" (progress-aware run_tasks)
_HEARTBEAT_S = 2.0


class _Heartbeat:
    """Touch `path` every _HEARTBEAT_S seconds on a daemon thread for
    the duration of a `with` block."""

    def __init__(self, path: str):
        import threading

        self._path = path
        self._stop = threading.Event()
        self._t = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        while not self._stop.wait(_HEARTBEAT_S):
            if chaos.ACTIVE:
                try:
                    # chaos seam: a stalled/dead heartbeat thread - the
                    # driver's progress-aware liveness must notice
                    chaos.fire("cluster.heartbeat", path=self._path)
                except Exception:  # noqa: BLE001 - injected stall
                    return
            try:
                os.utime(self._path)
            except OSError:
                return  # file gone: task finished racing us

    def __enter__(self):
        self._t.start()
        return self

    def __exit__(self, *exc):
        self._stop.set()
        self._t.join(timeout=2 * _HEARTBEAT_S)
        return False


def _rewrite_worker_local(blob: bytes, data_dir: str):
    """Rewrite __WORKER_LOCAL__ shuffle paths in a TaskDefinition to this
    worker's private data directory; returns (new blob, local outputs).
    Drivers use the token when they cannot know which worker will claim
    the map task (disjoint spool dirs, no shared data filesystem)."""
    from blaze_tpu.plan import plan_pb2 as pb

    t = pb.TaskDefinitionProto()
    t.ParseFromString(blob)
    outputs = []

    def walk(plan):
        kind = plan.WhichOneof("kind")
        if kind is None:
            return
        node = getattr(plan, kind)
        if kind == "shuffle_writer":
            for attr in ("data_file", "index_file"):
                v = getattr(node, attr)
                if v.startswith(WORKER_LOCAL_PREFIX):
                    setattr(
                        node, attr,
                        os.path.join(
                            data_dir,
                            v[len(WORKER_LOCAL_PREFIX):].lstrip("/"),
                        ),
                    )
            outputs.append((node.data_file, node.index_file))
        for field, value in node.ListFields():
            if field.message_type is None:
                continue
            if field.message_type.name == "PlanProto":
                if field.label == field.LABEL_REPEATED:
                    for sub in value:
                        walk(sub)
                else:
                    walk(value)

    walk(t.plan)
    if not outputs:
        return blob, []
    return t.SerializeToString(), outputs


def worker_main(spool: str, data_dir: Optional[str] = None) -> int:
    import jax

    jax.config.update("jax_enable_x64", True)

    from blaze_tpu.io.ipc import encode_ipc_segment, partition_ranges
    from blaze_tpu.runtime.executor import execute_task
    from blaze_tpu.runtime.transport import BlockServer

    data_dir = data_dir or tempfile.mkdtemp(prefix="blz-worker-")
    os.makedirs(data_dir, exist_ok=True)
    # multi-host: bind/advertise a routable address via env (loopback
    # only works when every worker shares this machine)
    bind_host = os.environ.get("BLAZE_WORKER_BIND_HOST", "127.0.0.1")
    server = BlockServer([data_dir], host=bind_host).start()
    host, port = server.address
    host = os.environ.get("BLAZE_WORKER_ADVERTISE_HOST", host)

    tasks_dir = os.path.join(spool, "tasks")
    claimed_dir = os.path.join(spool, "claimed")
    out_dir = os.path.join(spool, "out")
    quarantine_marker = os.path.join(
        spool, "quarantine", str(os.getpid())
    )
    while not os.path.exists(os.path.join(spool, "SHUTDOWN")):
        if os.path.exists(quarantine_marker):
            # the driver quarantined this slot after repeated
            # classified-fatal failures: stop claiming, keep serving
            # already-written shuffle blocks until shutdown
            time.sleep(0.2)
            continue
        claimed = None
        for name in sorted(os.listdir(tasks_dir)):
            if name.startswith("."):
                continue
            src = os.path.join(tasks_dir, name)
            dst = os.path.join(claimed_dir, name)
            try:
                os.replace(src, dst)  # atomic claim
                claimed = (name, dst)
                break
            except FileNotFoundError:
                continue  # another worker won the race
        if claimed is None:
            time.sleep(0.05)
            continue
        name, path = claimed
        if os.path.exists(quarantine_marker):
            # quarantined between the loop-top check and the claim
            # (the driver writes the marker BEFORE re-spooling a
            # failed task): return the task for a healthy worker
            # instead of burning its retry budget here
            try:
                os.replace(path, os.path.join(tasks_dir, name))
            except OSError:
                pass
            continue
        # obs: with tracing on (BLAZE_TRACE inherited from the driver
        # env), the worker records its own span subtree and ships it
        # in the result/.err payload - the driver grafts it so one
        # query renders as a single cross-process trace
        tracer = (
            obs_trace.begin_trace(name, root_name="worker_task")
            if obs_trace.ACTIVE else None
        )
        try:
            with open(path, "rb") as f:
                blob = f.read()
            blob, outputs = _rewrite_worker_local(blob, data_dir)
            parts = bytearray()
            with _Heartbeat(path):
                with (obs_trace.span("execute", rec=tracer, task=name)
                      if tracer is not None else obs_trace.NULL):
                    for rb in execute_task(blob):
                        parts += encode_ipc_segment(rb)
            if tracer is not None:
                tracer.finish(state="DONE")
            with open(os.path.join(out_dir, name + ".ipc"), "wb") as f:
                f.write(bytes(parts))
            meta = {
                "host": host,
                "port": port,
                "outputs": [
                    {
                        "data": data,
                        "index": index,
                        "ranges": [
                            list(r) for r in partition_ranges(index)
                        ],
                    }
                    for data, index in outputs
                    if os.path.exists(index)
                ],
            }
            if tracer is not None:
                meta["spans"] = tracer.to_dicts()
            with open(
                os.path.join(out_dir, name + ".meta.json"), "w"
            ) as f:
                json.dump(meta, f)
            open(os.path.join(out_dir, name + ".done"), "w").close()
        except Exception as e:  # report back to the driver, classified
            import traceback

            from blaze_tpu.errors import classify

            payload = {
                "pid": os.getpid(),
                "class": classify(e).value,
                "error": f"{type(e).__name__}: {e}",
                "traceback": traceback.format_exc(),
            }
            if tracer is not None:
                tracer.finish(state="FAILED",
                              error_class=classify(e).value)
                payload["spans"] = tracer.to_dicts()
            # atomic publish (like the task spool): the driver polls
            # every 50ms and a torn read would misclassify a TRANSIENT
            # failure as run-fatal INTERNAL
            tmp = os.path.join(out_dir, f".{name}.err.tmp")
            with open(tmp, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, os.path.join(out_dir, name + ".err"))
    server.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(
        worker_main(
            sys.argv[1], sys.argv[2] if len(sys.argv) > 2 else None
        )
    )
