"""Columnar batch substrate.

A `ColumnBatch` is the engine's unit of data flow - the TPU-native equivalent
of the reference's Arrow `RecordBatch` streaming through DataFusion operators
(reference exec.rs:196-255 hot loop). Differences, by design (SURVEY 7):

- Every column is a fixed-capacity device array padded up to a shape bucket,
  so XLA compiles one kernel per (pipeline, bucket) rather than per batch.
  The live row count is carried separately (`num_rows`); rows past it are
  padding with unspecified contents that kernels mask out.
- SQL NULLs are a separate bool validity array per column (None == all
  valid), matching Arrow validity semantics without bit-packing (TPU
  vectorizes bool arrays fine; bit-unpacking would serialize).
- utf8/binary columns are dictionary-encoded at the host boundary: int32
  codes on device + a host-side pyarrow dictionary. All device compute
  (group-by, join keys, comparisons) happens on codes or on 32-bit hashes
  computed from the real bytes by the host runtime.

`ColumnBatch` itself is a host object, NOT a pytree: jitted pipelines receive
the flat list of device arrays (`device_buffers()`) plus the row count, and
the host wrapper reassembles. This keeps non-traceable state (dictionaries,
schema) out of jit caching keys.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from blaze_tpu.config import get_config
from blaze_tpu.types import (
    DataType,
    Field,
    Schema,
    TypeId,
    from_arrow_schema,
    to_arrow_type,
)


@dataclasses.dataclass
class Column:
    """One column: padded device values + optional validity + host dict."""

    dtype: DataType
    values: jax.Array  # physical dtype, shape (capacity,)
    validity: Optional[jax.Array] = None  # bool, shape (capacity,) or None
    dictionary: Optional[object] = None  # pyarrow Array for utf8/binary

    @property
    def capacity(self) -> int:
        return self.values.shape[0]

    def valid_mask(self, capacity: Optional[int] = None) -> jax.Array:
        if self.validity is not None:
            return self.validity
        return jnp.ones(capacity or self.capacity, dtype=jnp.bool_)


@dataclasses.dataclass
class ColumnBatch:
    """Batch of padded device columns.

    `selection` is an optional device-resident row mask (the deferred
    selection vector of SURVEY 7): a row is live iff its index < num_rows
    AND selection[i]. Filters set it lazily so no host sync happens
    mid-pipeline; pipeline breakers (sort/aggregate/join/exchange) and the
    host boundary compact it away.
    """

    schema: Schema
    columns: List[Column]
    num_rows: int
    selection: Optional[jax.Array] = None

    @property
    def capacity(self) -> int:
        if not self.columns:
            return 0
        return self.columns[0].capacity

    @property
    def num_columns(self) -> int:
        return len(self.columns)

    def column(self, name_or_index) -> Column:
        if isinstance(name_or_index, int):
            return self.columns[name_or_index]
        return self.columns[self.schema.index_of(name_or_index)]

    # ------------------------------------------------------------------
    # flat device-buffer view for jitted pipelines
    # ------------------------------------------------------------------
    def device_buffers(self) -> List[jax.Array]:
        """Flat list of device arrays: [v0, m0?, v1, m1?, ...].

        The layout (which columns carry validity) is part of the batch's
        `layout()` descriptor, which jit-compiled pipelines key on.
        """
        bufs: List[jax.Array] = []
        for c in self.columns:
            bufs.append(c.values)
            if c.validity is not None:
                bufs.append(c.validity)
        return bufs

    def layout(self) -> Tuple:
        """Hashable descriptor of the device-buffer layout (jit cache key)."""
        return (
            self.capacity,
            tuple(
                (c.dtype.id.value, c.dtype.precision, c.dtype.scale,
                 c.validity is not None)
                for c in self.columns
            ),
        )

    @staticmethod
    def from_device_buffers(
        schema: Schema,
        layout: Tuple,
        bufs: Sequence[jax.Array],
        num_rows: int,
        dictionaries: Optional[Sequence[Optional[object]]] = None,
    ) -> "ColumnBatch":
        _, col_layout = layout
        cols: List[Column] = []
        it = iter(bufs)
        for i, (tid, prec, scale, has_mask) in enumerate(col_layout):
            dt = DataType(TypeId(tid), prec, scale)
            values = next(it)
            validity = next(it) if has_mask else None
            d = dictionaries[i] if dictionaries else None
            cols.append(Column(dt, values, validity, d))
        return ColumnBatch(schema, cols, num_rows)

    def dictionaries(self) -> List[Optional[object]]:
        return [c.dictionary for c in self.columns]

    # ------------------------------------------------------------------
    # host boundary: pyarrow interop
    # ------------------------------------------------------------------
    @staticmethod
    def from_arrow(rb, capacity: Optional[int] = None) -> "ColumnBatch":
        """Build from a pyarrow RecordBatch (dictionary-encode strings,
        pad to a shape bucket, move to device)."""
        import pyarrow as pa
        import pyarrow.compute as pc

        schema = from_arrow_schema(rb.schema)
        n = rb.num_rows
        cap = capacity or get_config().bucket_for(n)
        # (vals, cap, tail_fill) triples: padding and transfer-packing
        # fuse into one host copy (pack.put_packed_padded)
        entries: List[Tuple[np.ndarray, int, int]] = []
        col_meta: List[Tuple[DataType, bool, Optional[object]]] = []
        for i, field in enumerate(schema):
            arr = rb.column(i)
            if isinstance(arr, pa.ChunkedArray):
                arr = arr.combine_chunks()
            dt = field.dtype
            has_nulls = arr.null_count > 0
            null_np = np.asarray(arr.is_null()) if has_nulls else None
            dictionary = None
            if dt.is_dictionary_encoded:
                if not pa.types.is_dictionary(arr.type):
                    arr = pc.dictionary_encode(arr)
                dictionary = arr.dictionary
                np_vals = arr.indices.fill_null(0).to_numpy(
                    zero_copy_only=False)
                np_vals = np.ascontiguousarray(np_vals, dtype=np.int32)
            elif dt.id is TypeId.DECIMAL:
                if dt.is_wide_decimal:
                    np_vals = _decimal_limbs(arr)  # (n, 2) [lo, hi]
                else:
                    np_vals = _decimal_unscaled_i64(arr)
            elif dt.id is TypeId.TIMESTAMP_US:
                arr = arr.cast(pa.timestamp("us"))
                np_vals = arr.to_numpy(zero_copy_only=False).astype(
                    "datetime64[us]").view(np.int64)
            elif dt.id is TypeId.DATE32:
                np_vals = arr.to_numpy(zero_copy_only=False).astype(
                    "datetime64[D]").view(np.int64).astype(np.int32)
            elif dt.id is TypeId.NULL:
                np_vals = np.zeros(n, dtype=np.int8)
            else:
                if has_nulls:
                    # pyarrow surfaces nullable ints as float64 with NaN;
                    # fill first (nulls are tracked in validity anyway).
                    arr = arr.fill_null(
                        False if dt.id is TypeId.BOOL else 0)
                np_vals = arr.to_numpy(zero_copy_only=False)
            phys = dt.physical_dtype()
            if np_vals.dtype != phys:
                np_vals = np_vals.astype(phys)
            entries.append((np_vals, cap, 0))
            has_validity = has_nulls or dt.id is TypeId.NULL
            if has_validity:
                if dt.id is TypeId.NULL:
                    # all-invalid including the padding tail
                    entries.append((np.zeros(0, dtype=bool), cap, 0))
                else:
                    entries.append(
                        (~null_np, cap, 1)  # padding rows stay "valid"
                    )
            col_meta.append((dt, has_validity, dictionary, True))
        from blaze_tpu.runtime.pack import put_packed_padded_lazy

        buf, metas, pairs = put_packed_padded_lazy(entries)
        if buf is None:  # zero-column schema
            return ColumnBatch(schema, [], n)
        return PackedColumnBatch(schema, n, cap, buf, metas, pairs,
                                 col_meta)

    @staticmethod
    def from_arrow_pruned(rb, schema: Schema, present: Sequence[int],
                          capacity: Optional[int] = None) -> "ColumnBatch":
        """Build a batch with `schema` positions intact from a RecordBatch
        holding only the columns at `present` (ascending). Pruned
        positions get zero placeholders - never decoded, never
        transferred (constant-folded zeros inside fused kernels, shared
        device arrays on the classic path) - valid only when no consumer
        reads them (guaranteed by planner/colprune's conservative
        analysis)."""
        sub = ColumnBatch.from_arrow(rb, capacity)
        pres = set(present)
        if isinstance(sub, PackedColumnBatch) and sub.is_packed:
            # keep the packed wire buffer lazy: a fused consumer splices
            # unpack + placeholders + its whole chain into one dispatch
            it = iter(sub._col_meta)
            full_meta = []
            for i, field in enumerate(schema):
                if i in pres:
                    full_meta.append(next(it))
                else:
                    full_meta.append((field.dtype, False, None, False))
            return PackedColumnBatch(
                schema, rb.num_rows, sub.capacity, sub._buf,
                sub._metas, sub._pairs, full_meta,
            )
        cap = sub.capacity if sub.columns else (
            capacity or get_config().bucket_for(rb.num_rows)
        )
        it = iter(sub.columns)
        cols: List[Column] = []
        for i, field in enumerate(schema):
            if i in pres:
                cols.append(next(it))
            else:
                cols.append(
                    Column(field.dtype, _placeholder(cap, field.dtype))
                )
        return ColumnBatch(schema, cols, rb.num_rows)

    def live_mask(self) -> jax.Array:
        m = row_mask(self.num_rows, self.capacity)
        if self.selection is not None:
            m = m & self.selection
        return m

    def to_arrow(self):
        """Materialize the live rows back to a pyarrow RecordBatch.

        All device buffers travel in ONE packed transfer (a single device
        round trip regardless of column count), sliced on device to the
        smallest shape bucket covering the live rows so padding beyond it
        never crosses the wire."""
        import pyarrow as pa

        from blaze_tpu.runtime.pack import get_packed

        cap = self.capacity
        k = None
        if cap and self.num_rows < cap:
            k = min(get_config().bucket_for(self.num_rows), cap)
            if k >= cap:
                k = None
        device_bufs = [self.selection] + self.device_buffers()
        host_bufs = get_packed(device_bufs, slice_rows=k)
        host_sel, host_iter = host_bufs[0], iter(host_bufs[1:])
        host_cols = []
        for c in self.columns:
            v = next(host_iter)
            m = next(host_iter) if c.validity is not None else None
            host_cols.append((v, m))

        n = self.num_rows
        sel = None
        if self.selection is not None:
            sel = np.asarray(host_sel)[:n]
            n = int(sel.sum())
        arrays = []
        fields = []
        for field, col, (hv, hm) in zip(
            self.schema, self.columns, host_cols
        ):
            vals = np.asarray(hv)[: self.num_rows]
            mask = None
            if hm is not None:
                mask = ~np.asarray(hm)[: self.num_rows]
            if sel is not None:
                vals = vals[sel]
                if mask is not None:
                    mask = mask[sel]
            dt = field.dtype
            if dt.is_dictionary_encoded:
                codes = vals.astype(np.int32)
                if mask is not None:
                    codes = np.where(mask, 0, codes)
                dict_arr = col.dictionary
                if dict_arr is None and len(codes):
                    # pruned placeholder column (codes=0 with no
                    # dictionary) reaching a materializing consumer
                    # (DebugExec logging, sort spill, grace-join
                    # externalization): render all-null rather than
                    # indexing an empty dictionary - the values were
                    # never read, so nulls are the honest rendering
                    arr = pa.nulls(len(codes), type=to_arrow_type(dt))
                else:
                    if dict_arr is None:
                        dict_arr = pa.array([], type=to_arrow_type(dt))
                    indices = pa.array(codes, mask=mask)
                    arr = pa.DictionaryArray.from_arrays(
                        indices, dict_arr
                    ).cast(to_arrow_type(dt))
            elif dt.id is TypeId.DECIMAL:
                if vals.ndim == 2:
                    arr = _decimal_from_limbs(
                        vals.astype(np.int64), mask,
                        dt.precision, dt.scale,
                    )
                else:
                    arr = _decimal_from_unscaled_i64(
                        vals.astype(np.int64), mask,
                        dt.precision, dt.scale,
                    )
            elif dt.id is TypeId.DATE32:
                arr = pa.array(
                    vals.astype(np.int32), mask=mask, type=pa.int32()
                ).cast(pa.date32())
            elif dt.id is TypeId.TIMESTAMP_US:
                arr = pa.array(
                    vals.astype(np.int64), mask=mask, type=pa.int64()
                ).cast(pa.timestamp("us"))
            elif dt.id is TypeId.NULL:
                arr = pa.nulls(n)
            else:
                arr = pa.array(vals, mask=mask, type=to_arrow_type(dt))
            arrays.append(arr)
            fields.append(pa.field(field.name, arr.type, field.nullable))
        return pa.RecordBatch.from_arrays(arrays, schema=pa.schema(fields))

    @staticmethod
    def from_pydict(data: dict, schema: Optional[Schema] = None,
                    capacity: Optional[int] = None) -> "ColumnBatch":
        """Test/interop helper: build from {name: list} via pyarrow."""
        import pyarrow as pa

        if schema is not None:
            from blaze_tpu.types import to_arrow_schema

            rb = pa.RecordBatch.from_pydict(
                data, schema=to_arrow_schema(schema)
            )
        else:
            rb = pa.RecordBatch.from_pydict(data)
        return ColumnBatch.from_arrow(rb, capacity=capacity)

    def to_pydict(self) -> dict:
        return self.to_arrow().to_pydict()

    # ------------------------------------------------------------------
    def slice_host(self, start: int, length: int) -> "ColumnBatch":
        """Host-side row slice (used by spill/IPC writers)."""
        rb = self.to_arrow().slice(start, length)
        return ColumnBatch.from_arrow(rb)


class PackedColumnBatch(ColumnBatch):
    """A ColumnBatch whose device columns still live inside the single
    packed H2D wire buffer (runtime/pack.put_packed_padded_lazy).

    Two consumption modes:

    - `packed_view()` (pipeline fusion): the fused operator composes the
      buffer splitter into its OWN jitted kernel, so transfer-unpack +
      the whole operator chain is ONE dispatch per batch. Pruned scan
      positions materialize as jnp.zeros inside the kernel - XLA folds
      the constants and dead-codes unread columns.
    - `.columns` / `device_buffers()` (any classic operator): first
      access runs the shared cached unpack kernel once (exactly the old
      put_packed_padded dispatch) and the batch behaves as a plain
      ColumnBatch thereafter.

    `col_meta` is `[(dtype, has_validity, dictionary, packed)]` per
    schema position; `packed=False` marks a colprune placeholder that has
    no segment in the wire buffer."""

    def __init__(self, schema: Schema, num_rows: int, cap: int,
                 buf: jax.Array, metas: Tuple, pairs: bool, col_meta):
        self.schema = schema
        self.num_rows = num_rows
        self.selection = None
        self._cap = cap
        self._buf = buf
        self._metas = metas
        self._pairs = pairs
        self._col_meta = list(col_meta)
        self._cols: Optional[List[Column]] = None

    # -- lazy plain-batch view ----------------------------------------
    @property
    def is_packed(self) -> bool:
        return self._cols is None

    @property
    def columns(self) -> List[Column]:  # type: ignore[override]
        if self._cols is None:
            self._unpack()
        return self._cols

    @columns.setter
    def columns(self, cols) -> None:
        self._cols = list(cols)

    @property
    def capacity(self) -> int:
        return self._cap

    def layout(self) -> Tuple:
        return (
            self._cap,
            tuple(
                (dt.id.value, dt.precision, dt.scale, has_validity)
                for dt, has_validity, _, _ in self._col_meta
            ),
        )

    def dictionaries(self) -> List[Optional[object]]:
        return [d for _, _, d, _ in self._col_meta]

    def _unpack(self) -> None:
        from blaze_tpu.runtime.pack import unpack_kernel

        arrays = iter(unpack_kernel(self._metas, self._pairs)(self._buf))
        cols: List[Column] = []
        for dt, has_validity, dictionary, packed in self._col_meta:
            if not packed:
                cols.append(Column(dt, _placeholder(self._cap, dt)))
                continue
            values = next(arrays)
            validity = next(arrays) if has_validity else None
            cols.append(Column(dt, values, validity, dictionary))
        self._cols = cols

    # -- fused-kernel view --------------------------------------------
    def packed_view(self) -> Optional["PackedView"]:
        """The fusion contract, or None once the batch was unpacked."""
        if self._cols is not None:
            return None
        return PackedView(
            self._buf,
            (
                self._metas,
                self._pairs,
                tuple(
                    (dt.id.value, dt.precision, dt.scale,
                     has_validity, packed)
                    for dt, has_validity, _, packed in self._col_meta
                ),
            ),
            self._build_unflatten,
            self.layout(),
        )

    def _build_unflatten(self):
        from blaze_tpu.runtime.pack import build_unpack_at

        split = build_unpack_at(self._metas, self._pairs)
        # capture only what unflatten reads: the closure lives in the
        # process-global kernel cache, so it must not pin this batch's
        # pyarrow dictionaries in host memory
        col_meta = [
            (dt, has_validity, packed)
            for dt, has_validity, _, packed in self._col_meta
        ]
        cap = self._cap

        def unflatten(u8):
            arrays = iter(split(u8))
            bufs: List[jax.Array] = []
            for dt, has_validity, packed in col_meta:
                if not packed:
                    phys = dt.physical_dtype()
                    shape = (
                        (cap, 2) if dt.is_wide_decimal else (cap,)
                    )
                    bufs.append(jnp.zeros(shape, dtype=phys))
                    continue
                bufs.append(next(arrays))
                if has_validity:
                    bufs.append(next(arrays))
            return bufs

        return unflatten


@dataclasses.dataclass(frozen=True)
class PackedView:
    """What a fused kernel needs from a still-packed batch: the wire
    buffer (the kernel's traced input), a hashable cache-key component,
    a builder returning the traceable u8 -> device_buffers splitter, and
    the batch's layout descriptor (feeds the classic inner kernel)."""

    buf: jax.Array = dataclasses.field(compare=False)
    key: Tuple = ()
    build_unflatten: object = dataclasses.field(
        default=None, compare=False
    )
    layout: Tuple = ()


def packed_view(cb: ColumnBatch) -> Optional[PackedView]:
    """PackedView of a batch when fusion can consume it directly."""
    if isinstance(cb, PackedColumnBatch):
        return cb.packed_view()
    return None


import collections
import threading
import weakref

_PLACEHOLDER_CACHE: "collections.OrderedDict" = collections.OrderedDict()
_PLACEHOLDER_CACHE_CAP = 32
_PLACEHOLDER_TRACK_ID = id(_PLACEHOLDER_CACHE)
_PLACEHOLDER_LOCK = threading.Lock()


def _placeholder(cap: int, dtype: DataType) -> jax.Array:
    """Shared all-zeros device column for pruned (never-read) scan
    positions. Safe to share across batches/plans: engine kernels are
    pure functions and never mutate input buffers. LRU-bounded (under a
    lock - prefetch worker threads race here) and accounted in the
    device-memory tracker so grace/spill budgeting sees the pinned HBM.
    Evicted arrays release their tracked bytes via weakref finalizer -
    only once the LAST in-flight batch referencing them drops - so the
    accounting never under-counts live HBM."""
    phys = dtype.physical_dtype()
    shape = (cap, 2) if dtype.is_wide_decimal else (cap,)
    key = (shape, str(phys))
    with _PLACEHOLDER_LOCK:
        arr = _PLACEHOLDER_CACHE.get(key)
        if arr is not None:
            _PLACEHOLDER_CACHE.move_to_end(key)
            return arr
    from blaze_tpu.runtime.memory import get_device_tracker

    new = jnp.zeros(shape, dtype=phys)
    tracker = get_device_tracker()
    with _PLACEHOLDER_LOCK:
        arr = _PLACEHOLDER_CACHE.get(key)
        if arr is not None:  # lost a double-miss race: reuse, drop ours
            _PLACEHOLDER_CACHE.move_to_end(key)
            return arr
        _PLACEHOLDER_CACHE[key] = new
        tracker.track(_PLACEHOLDER_TRACK_ID, int(new.nbytes))
        evicted = []
        while len(_PLACEHOLDER_CACHE) > _PLACEHOLDER_CACHE_CAP:
            _, old = _PLACEHOLDER_CACHE.popitem(last=False)
            evicted.append(old)
    for old in evicted:
        nbytes = int(old.nbytes)
        try:
            # release only when the last in-flight reference drops
            weakref.finalize(
                old, tracker.release, _PLACEHOLDER_TRACK_ID, nbytes
            )
        except TypeError:  # object not weak-referenceable
            tracker.release(_PLACEHOLDER_TRACK_ID, nbytes)
    return new


def _decimal_unscaled_i64(arr) -> np.ndarray:
    """Extract decimal128 unscaled values that fit in i64 (the engine's
    decimal representation; matches the reference's i64-only decimals,
    plan.proto:598-601)."""
    import pyarrow as pa

    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    buf = arr.buffers()[1]
    if buf is None:
        return np.zeros(len(arr), dtype=np.int64)
    raw = np.frombuffer(buf, dtype=np.int64)
    # decimal128 is 16 bytes little-endian; low limb is the i64 value for
    # anything within i64 range.
    lo = raw[arr.offset * 2::2][: len(arr)]
    return np.ascontiguousarray(lo)


def _decimal_limbs(arr) -> np.ndarray:
    """(n, 2) little-endian int64 limbs [lo bit-pattern, hi] of a
    decimal128 array - the full 16-byte representation."""
    import pyarrow as pa

    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    buf = arr.buffers()[1]
    n = len(arr)
    if buf is None:
        return np.zeros((n, 2), dtype=np.int64)
    raw = np.frombuffer(buf, dtype=np.int64)
    start = arr.offset * 2
    return np.ascontiguousarray(
        raw[start: start + 2 * n].reshape(n, 2)
    )


def _decimal_from_limbs(vals: np.ndarray, mask, precision: int,
                        scale: int):
    """(n, 2) [lo, hi] limbs -> Decimal128Array."""
    import pyarrow as pa

    n = len(vals)
    data = pa.py_buffer(np.ascontiguousarray(vals).tobytes())
    if mask is not None:
        validity = pa.array(~mask).buffers()[1]
    else:
        validity = None
    return pa.Array.from_buffers(
        pa.decimal128(precision, scale), n, [validity, data]
    )


def _decimal_from_unscaled_i64(vals: np.ndarray, mask, precision: int,
                               scale: int):
    """Inverse of _decimal_unscaled_i64: i64 unscaled -> Decimal128Array."""
    import pyarrow as pa

    n = len(vals)
    limbs = np.zeros(2 * n, dtype=np.int64)
    limbs[0::2] = vals  # low limb, little-endian
    limbs[1::2] = np.where(vals < 0, -1, 0)  # sign extension
    data = pa.py_buffer(limbs.tobytes())
    if mask is not None:
        validity = pa.array(~mask).buffers()[1]
    else:
        validity = None
    return pa.Array.from_buffers(
        pa.decimal128(precision, scale), n, [validity, data]
    )


def empty_batch(schema: Schema, capacity: Optional[int] = None) -> ColumnBatch:
    cap = capacity if capacity is not None else get_config().shape_buckets[0]
    cols = []
    for f in schema:
        phys = f.dtype.physical_dtype()
        shape = (cap, 2) if f.dtype.is_wide_decimal else (cap,)
        cols.append(
            Column(f.dtype, jnp.zeros(shape, dtype=phys), None, None)
        )
    return ColumnBatch(schema, cols, 0)


def row_mask(num_rows, capacity: int) -> jax.Array:
    """Mask of live rows for a padded batch; `num_rows` may be traced."""
    return jnp.arange(capacity, dtype=jnp.int32) < num_rows
