"""Engine configuration.

Mirrors the reference's engine-sizing knobs (spark.blaze.batchSize, memory
fraction, tmp dirs: reference NativeSupports.scala:241-253 -> exec.rs:53-107)
plus TPU-specific sizing (shape buckets, device memory budget).
"""

from __future__ import annotations

import dataclasses
import os
import tempfile
from typing import Optional, Sequence


@dataclasses.dataclass
class EngineConfig:
    # Max rows per device batch (reference default 16384, exec.rs:105).
    batch_size: int = 16384
    # Fraction of the memory budget the engine may use before spilling
    # (reference MemoryManagerConfig memory_fraction, exec.rs:79-94).
    memory_fraction: float = 0.75
    # Total host-side memory budget in bytes for buffered shuffle/agg state.
    max_memory: int = 4 << 30
    # Device (HBM) budget for resident partition buffers before host spill.
    device_memory_budget: int = 8 << 30
    # Spill directories (reference DiskManagerConfig::NewSpecified tmp_dirs).
    tmp_dirs: Sequence[str] = dataclasses.field(
        default_factory=lambda: [tempfile.gettempdir()]
    )
    # Row-count buckets for padding batches to static shapes. Each batch is
    # padded up to the smallest bucket >= its row count so XLA compiles one
    # kernel per (pipeline, bucket) instead of per exact shape.
    shape_buckets: Sequence[int] = (256, 1024, 4096, 16384)
    # zstd level for segmented-IPC shuffle segments (reference uses level 1,
    # util/ipc.rs:20-49).
    ipc_compression_level: int = 1
    # Default shuffle partition count when a plan does not specify one.
    default_shuffle_partitions: int = 200
    # Pipeline-breaker materialization cap: aggregates/joins whose input
    # exceeds this many rows switch to external (grace) hash-bucketed
    # execution through the segmented-IPC spill format (ops/external.py).
    max_materialize_rows: int = 1 << 22
    # Bucket count for external execution.
    external_buckets: int = 32
    # Enable per-operator timing metrics.
    collect_metrics: bool = True
    # Static output capacity for grouped-aggregate kernels: state arrays
    # are sliced to this many group slots on device before leaving the
    # kernel, so a small result never transfers (or feeds downstream
    # kernels at) full input capacity. Overflow (more groups than slots)
    # re-dispatches an unsliced kernel - correctness never depends on it.
    agg_group_capacity: int = 65536
    # Grouping-core selection for hash aggregates: "scatter" (open-
    # addressing hash table built from scatter/gather, sort-free - the
    # O(n) path), "sort" (stable lexsort + boundary detection), or
    # "auto" (scatter on the CPU backend where an 8M-row sort costs
    # ~3.5s vs ~0.1s for the table; on TPU a same-chip VALIDATED
    # benchmarks/tpu_core_probe.json decides, falling back to sort
    # when no chip measurement exists - resolve_core_choice below).
    # Env override: BLAZE_GROUP_CORE.
    group_core: str = "auto"
    # Join-core selection for the unique-build fast path (hash-table
    # probe, no sort/searchsorted/pair-expansion): same choices and
    # rationale as group_core; auto-on-TPU rides the probe's group
    # measurement. Env override: BLAZE_JOIN_CORE.
    join_core: str = "auto"
    # Multi-key argsort selection: "scatter" here means the packed-u64
    # single-lane value sort (one XLA sort per key); "sort" the 3-lane
    # index lexsort ladder. "auto" = packed on CPU; on TPU the lexsort
    # ladder unless a same-chip probe artifact VALIDATED the packed
    # permutation there (the no-X64 rewrite pass lacks full u64
    # support, exprs/hashing.py:83 - timing alone never flips this).
    # Env override: BLAZE_SORT_CORE.
    sort_core: str = "auto"
    # Evaluate pushed-down filter conjuncts host-side during parquet
    # decode (pyarrow C++), compacting rows before padding/transfer.
    # Halves transfer bytes at 50% selectivity but costs host CPU; the
    # right default depends on the host->device link (keep on for a
    # network-attached chip, consider off when decode is the
    # bottleneck). Row-group STATS pruning is unaffected by this flag.
    host_filter_pushdown: bool = True

    def bucket_for(self, num_rows: int) -> int:
        for b in self.shape_buckets:
            if num_rows <= b:
                return b
        # Round up to a multiple of the largest bucket for oversized batches.
        top = self.shape_buckets[-1]
        return ((num_rows + top - 1) // top) * top

    def spill_dir(self) -> str:
        d = self.tmp_dirs[0]
        os.makedirs(d, exist_ok=True)
        return d


_PROBE_CACHE = None


def _probe_artifact():
    """The recorded on-chip core measurement, if one exists.

    bench.py's tpu_core_probe writes benchmarks/tpu_core_probe.json
    when it reaches a real chip (the end-of-round driver run); `auto`
    core choices then derive from MEASURED data instead of a guess.
    Absent/stale file -> None and the heuristic stands."""
    global _PROBE_CACHE
    if _PROBE_CACHE is not None:
        return _PROBE_CACHE or None
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "benchmarks", "tpu_core_probe.json",
    )
    probe = {}
    try:
        with open(path) as f:
            import json

            probe = json.load(f)
    except Exception:  # noqa: BLE001 - missing/corrupt = no data
        probe = {}
    if not isinstance(probe, dict):
        probe = {}
    _PROBE_CACHE = probe or False
    return probe or None


def resolve_core_choice(env_var: str, cfg_value: str) -> str:
    """Shared resolution for the grouping/join core knobs: env override
    beats config; "auto" picks the scatter core on CPU (where the sort
    it replaces costs 20-35x more) and on TPU consults the recorded
    tpu_core_probe artifact when one exists (falling back to sort, the
    conservative guess, when no chip measurement was ever captured).
    Unknown values raise so a typo'd knob can't silently measure the
    wrong core."""
    mode = os.environ.get(env_var) or cfg_value
    if mode not in ("auto", "scatter", "sort"):
        raise ValueError(
            f"{env_var}/config must be auto|scatter|sort, got {mode!r}"
        )
    if mode == "auto":
        import jax

        if jax.default_backend() == "cpu":
            return "scatter"
        probe = _probe_artifact()
        if probe:
            # the probe measures the group and sort cores; the join
            # knob rides the group result (same scatter-table
            # machinery). Trust requires BOTH (a) the artifact came
            # from THIS chip generation and (b) the probe
            # cross-validated the two cores' outputs on it - timing
            # alone never flips a core (the packed-u64 sort path in
            # particular is correctness-gated on TPU's partial i64
            # support, so an unvalidated fast time must not select it)
            try:
                same_chip = (
                    probe.get("device_kind")
                    == jax.devices()[0].device_kind
                )
            except Exception:  # noqa: BLE001
                same_chip = False
            kind = "sort" if "SORT" in env_var else "group"
            sc = probe.get(f"{kind}_scatter_s")
            so = probe.get(f"{kind}_sort_s")
            if (same_chip
                    and probe.get(f"{kind}_valid") is True
                    and isinstance(sc, (int, float))
                    and isinstance(so, (int, float))):
                return "scatter" if sc <= so else "sort"
        return "sort"
    return mode


_CONFIG: EngineConfig = EngineConfig()


def get_config() -> EngineConfig:
    return _CONFIG


def set_config(cfg: EngineConfig) -> EngineConfig:
    global _CONFIG
    _CONFIG = cfg
    return cfg
