"""Host IO: segmented Arrow-IPC exchange format, shuffle files."""
