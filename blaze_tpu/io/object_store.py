"""Object store abstraction for scan IO.

Reference counterpart: the HDFS object store proxy
(hdfs_object_store.rs:34-140) - the reference's native engine never talks
to storage directly; it registers an ObjectStore whose get_range/head call
back into the embedding JVM's Hadoop FileSystem, with the real path
smuggled through a base64 `hdfs://-/` prefix
(hdfs_object_store.rs:173-190, NativeParquetScanExec.scala:70-76).

Here the same seams exist engine-side:
- `LocalStore` reads the local filesystem (the common case)
- `MemoryStore` serves registered in-memory blobs (tests, spill-less runs)
- `CallbackStore` proxies `get_range`/`size` to an embedder-supplied
  function - the JVM-FS-proxy analog for paths the engine cannot reach
  (HDFS behind a JVM, object stores with embedder-held credentials)
- `encode_smuggled_path`/`decode_smuggled_path` implement the base64
  `scheme://-/` convention so remote paths survive URL-hostile plumbing
"""

from __future__ import annotations

import base64
import io
import os
import threading
from typing import Callable, Dict, Optional

SMUGGLE_MARKER = "://-/"


def encode_smuggled_path(scheme: str, real_path: str) -> str:
    b64 = base64.urlsafe_b64encode(real_path.encode()).decode()
    return f"{scheme}{SMUGGLE_MARKER}{b64}"


def decode_smuggled_path(path: str) -> Optional[str]:
    if SMUGGLE_MARKER not in path:
        return None
    b64 = path.split(SMUGGLE_MARKER, 1)[1]
    return base64.urlsafe_b64decode(b64.encode()).decode()


class ObjectStore:
    def get_range(self, path: str, offset: int, length: int) -> bytes:
        raise NotImplementedError

    def size(self, path: str) -> int:
        raise NotImplementedError

    def open_input(self, path: str):
        """File-like object for readers that want one (pyarrow parquet)."""
        return _RangedFile(self, path)


class LocalStore(ObjectStore):
    def get_range(self, path: str, offset: int, length: int) -> bytes:
        with open(path, "rb") as f:
            f.seek(offset)
            return f.read(length)

    def size(self, path: str) -> int:
        return os.path.getsize(path)

    def open_input(self, path: str):
        # mmap'd parquet page buffers (zero-copy serve path): pyarrow's
        # reader slices column chunks straight out of the page cache
        # instead of read()-copying them. BLAZE_PARQUET_MMAP=0 opts
        # out; any failure (FS without mmap, chaos `zerocopy.map`
        # seam) degrades to the buffered-read path.
        if os.environ.get("BLAZE_PARQUET_MMAP", "1") != "0":
            try:
                import pyarrow as pa

                from blaze_tpu.testing import chaos

                if chaos.ACTIVE:
                    chaos.fire("zerocopy.map", path=path)
                return pa.memory_map(path, "r")
            except Exception:  # noqa: BLE001 - degrade to read path
                pass
        return open(path, "rb")


class MemoryStore(ObjectStore):
    def __init__(self):
        self._blobs: Dict[str, bytes] = {}

    def put(self, path: str, data: bytes) -> None:
        self._blobs[path] = bytes(data)

    def get_range(self, path: str, offset: int, length: int) -> bytes:
        return self._blobs[path][offset: offset + length]

    def size(self, path: str) -> int:
        return len(self._blobs[path])

    def open_input(self, path: str):
        return io.BytesIO(self._blobs[path])


class CallbackStore(ObjectStore):
    """Proxy reads to the embedder (the reference's JNI->Hadoop FS path,
    hdfs_object_store.rs:82-140: open/seek/read through JniBridge)."""

    def __init__(self, read_range: Callable[[str, int, int], bytes],
                 get_size: Callable[[str], int]):
        self._read = read_range
        self._size = get_size

    def get_range(self, path: str, offset: int, length: int) -> bytes:
        real = decode_smuggled_path(path) or path
        return self._read(real, offset, length)

    def size(self, path: str) -> int:
        real = decode_smuggled_path(path) or path
        return self._size(real)


class RemoteBlockStore(ObjectStore):
    """A REAL remote filesystem behind the scheme registry: ranged reads
    and stats over the engine's block-transport protocol
    (runtime/transport.BlockServer), with the retry/timeout hardening
    the reference delegates to its Hadoop client
    (hdfs_object_store.rs:82-140 proxies to JVM HDFS, which retries
    internally). Paths look like `blz://host:port/abs/path`; any worker
    whose BlockServer serves that path's root can be scanned remotely -
    parquet scans included (pyarrow's reader drives get_range).

    Retries: transient socket errors back off exponentially
    (base_delay * 2^attempt) up to `retries` attempts per request;
    PermissionError and protocol errors fail fast (a retry cannot fix
    them)."""

    def __init__(self, retries: int = 3, timeout: float = 30.0,
                 base_delay: float = 0.1):
        self.retries = retries
        self.timeout = timeout
        self.base_delay = base_delay

    @staticmethod
    def _parse(path: str):
        rest = path.split("://", 1)[1]
        loc, _, file_path = rest.partition("/")
        host, _, port = loc.rpartition(":")
        return host, int(port), "/" + file_path

    def _with_retries(self, fn):
        import time

        from blaze_tpu.runtime.transport import BlockProtocolError

        last = None
        for attempt in range(self.retries):
            try:
                return fn()
            except (BlockProtocolError, PermissionError):
                raise  # deterministic: a retry cannot fix these
            except (ConnectionError, TimeoutError, OSError) as e:
                last = e
                time.sleep(self.base_delay * (2 ** attempt))
        raise IOError(
            f"remote read failed after {self.retries} attempts: {last}"
        ) from last

    def get_range(self, path: str, offset: int, length: int) -> bytes:
        from blaze_tpu.runtime.transport import (
            RemoteSegment,
            open_remote_stream,
        )

        host, port, file_path = self._parse(path)

        def fetch():
            stream = open_remote_stream(
                RemoteSegment(host, port, file_path, offset, length),
                timeout=self.timeout,
            )
            try:
                return stream.read(-1)
            finally:
                stream.close()

        return self._with_retries(fetch)

    def size(self, path: str) -> int:
        from blaze_tpu.runtime.transport import remote_stat

        host, port, file_path = self._parse(path)
        return self._with_retries(
            lambda: remote_stat(host, port, file_path,
                                timeout=self.timeout)
        )


class _RangedFile(io.RawIOBase):
    """Seekable file-like view over an ObjectStore object (what pyarrow's
    parquet reader needs)."""

    def __init__(self, store: ObjectStore, path: str):
        self._store = store
        self._path = path
        self._pos = 0
        self._size = store.size(path)

    def readable(self) -> bool:
        return True

    def seekable(self) -> bool:
        return True

    def seek(self, offset: int, whence: int = 0) -> int:
        if whence == 0:
            self._pos = offset
        elif whence == 1:
            self._pos += offset
        else:
            self._pos = self._size + offset
        return self._pos

    def tell(self) -> int:
        return self._pos

    def read(self, n: int = -1) -> bytes:
        if n is None or n < 0:
            n = self._size - self._pos
        data = self._store.get_range(self._path, self._pos, n)
        self._pos += len(data)
        return data

    def readinto(self, b) -> int:
        data = self.read(len(b))
        b[: len(data)] = data
        return len(data)


# ---------------------------------------------------------------------------
# scheme registry (reference registers the hdfs store on the session
# context at init, exec.rs:96-103)
# ---------------------------------------------------------------------------

_REGISTRY: Dict[str, ObjectStore] = {}
_LOCAL = LocalStore()
_LOCK = threading.Lock()


def register_store(scheme: str, store: ObjectStore) -> None:
    with _LOCK:
        _REGISTRY[scheme] = store


def store_for(path: str) -> ObjectStore:
    if "://" in path:
        scheme = path.split("://", 1)[0]
        with _LOCK:
            st = _REGISTRY.get(scheme)
            if st is None and scheme == "blz":
                # the engine's own remote-FS scheme works out of the box
                # (the reference likewise registers its hdfs store at
                # session init, exec.rs:96-103)
                st = _REGISTRY[scheme] = RemoteBlockStore()
        if st is None:
            raise KeyError(
                f"no object store registered for scheme {scheme!r}"
            )
        return st
    return _LOCAL
