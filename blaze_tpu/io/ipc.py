"""Segmented Arrow-IPC exchange format.

Bit-compatible with the reference's on-disk/wire format so a Spark executor
can exchange shuffle and broadcast bytes with this engine (SURVEY 4 calls
this a bit-compatibility contract):

  part     := [u64 LE length][zstd(Arrow IPC stream)]      (util/ipc.rs:20-49)
  segment  := part*                                        (per partition)
  data     := segment per partition, concatenated
  index    := (num_partitions + 1) LE i64 start offsets
              (shuffle_writer_exec.rs:437-506, architectural_overview.md)

Empty batches write nothing (write_ipc_compressed returns 0). Readers skip
zero-length parts (IpcInputStreamIterator.scala:54-100 does the same).
"""

from __future__ import annotations

import io
import os
import struct
from typing import Iterator, List, Optional, Tuple

import pyarrow as pa

from blaze_tpu.runtime import native


def encode_ipc_segment(rb: pa.RecordBatch, level: int = 1) -> bytes:
    """One length-prefixed zstd Arrow-IPC part. Empty batch -> b''."""
    if rb.num_rows == 0:
        return b""
    sink = io.BytesIO()
    with pa.ipc.new_stream(sink, rb.schema) as writer:
        writer.write_batch(rb)
    compressed = native.zstd_compress(sink.getvalue(), level)
    return struct.pack("<Q", len(compressed)) + compressed


def decode_ipc_parts(buf: bytes) -> Iterator[pa.RecordBatch]:
    """Iterate RecordBatches out of a concatenated parts buffer."""
    pos = 0
    n = len(buf)
    while pos + 8 <= n:
        (length,) = struct.unpack_from("<Q", buf, pos)
        pos += 8
        if length == 0:
            continue
        frame = buf[pos: pos + length]
        pos += length
        raw = native.zstd_decompress(frame)
        if not raw:
            continue
        with pa.ipc.open_stream(raw) as reader:
            for rb in reader:
                if rb.num_rows > 0:
                    yield rb


def decode_ipc_stream(stream) -> Iterator[pa.RecordBatch]:
    """Incrementally decode parts from a file-like object (the remote
    shuffle-fetch path: the reference wraps a JVM ReadableByteChannel the
    same way, ipc_reader_exec.rs:283-326). Reads exactly one part at a
    time - memory stays bounded by the largest part."""
    while True:
        hdr = stream.read(8)
        if not hdr or len(hdr) < 8:
            return
        (length,) = struct.unpack("<Q", hdr)
        if length == 0:
            continue
        frame = b""
        while len(frame) < length:
            chunk = stream.read(length - len(frame))
            if not chunk:
                raise IOError("truncated IPC part in stream")
            frame += chunk
        raw = native.zstd_decompress(frame)
        if not raw:
            continue
        with pa.ipc.open_stream(raw) as reader:
            for rb in reader:
                if rb.num_rows > 0:
                    yield rb


def read_file_segment(path: str, offset: int, length: int
                      ) -> Iterator[pa.RecordBatch]:
    """Zero-copy-ish read of one partition's byte range from a .data file
    (the reference's local FileSegment fast path,
    ArrowBlockStoreShuffleReader301.scala:83-123)."""
    with open(path, "rb") as f:
        f.seek(offset)
        buf = f.read(length)
    yield from decode_ipc_parts(buf)


def read_index_file(path: str) -> List[int]:
    with open(path, "rb") as f:
        raw = f.read()
    count = len(raw) // 8
    return list(struct.unpack(f"<{count}q", raw))


def partition_ranges(index_path: str) -> List[Tuple[int, int]]:
    """(offset, length) per partition from an .index file."""
    offs = read_index_file(index_path)
    return [
        (offs[i], offs[i + 1] - offs[i]) for i in range(len(offs) - 1)
    ]
