"""Profile join_agg and grouped_agg shapes: dispatch counts + cProfile."""
import os, sys, time
os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, "/root/repo")
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
import pyarrow as pa

from blaze_tpu.config import EngineConfig, set_config

N = int(os.environ.get("N", 8 << 20))
chunk = min(N, 1 << 20)
set_config(EngineConfig(batch_size=chunk, shape_buckets=(4096, 65536, 1 << 20, chunk, N)))

from blaze_tpu.exprs import AggExpr, AggFn, Col
from blaze_tpu.exprs.ir import Literal
from blaze_tpu.ops import AggMode, HashAggregateExec, MemoryScanExec, ProjectExec
from blaze_tpu.ops.joins import HashJoinExec, JoinType
from blaze_tpu.ops.fused import fuse_pipelines
from blaze_tpu.runtime import dispatch
from blaze_tpu.runtime.executor import run_plan
from blaze_tpu.batch import ColumnBatch
from blaze_tpu.types import DataType

rng = np.random.default_rng(42)
n_items = 1 << 17
item_sk = rng.integers(0, n_items, N).astype(np.int32)
qty = rng.integers(1, 10, N).astype(np.int32)
price = (rng.random(N) * 100).astype(np.float32)
part_sk = rng.integers(0, 1 << 10, N).astype(np.int32)
i_item_sk = np.arange(n_items, dtype=np.int32)
i_brand = rng.integers(0, 4096, n_items).astype(np.int32)

fact_cb = ColumnBatch.from_arrow(pa.record_batch({"item": item_sk, "qty": qty, "price": price, "part": part_sk}))
item_cb = ColumnBatch.from_arrow(pa.record_batch({"i_item": i_item_sk, "i_brand": i_brand}))

def fact_scan(): return MemoryScanExec([[fact_cb]], fact_cb.schema)
def item_scan(): return MemoryScanExec([[item_cb]], item_cb.schema)

join_plan = fuse_pipelines(HashAggregateExec(
    ProjectExec(
        HashJoinExec(item_scan(), ProjectExec(fact_scan(), [(Col("item"), "item"), (Col("price"), "price")]),
                     [Col("i_item")], [Col("item")], JoinType.INNER),
        [(Col("i_brand"), "brand"), (Col("price"), "price")]),
    keys=[(Col("brand"), "brand")],
    aggs=[(AggExpr(AggFn.SUM, Col("price")), "rev"), (AggExpr(AggFn.COUNT_STAR, None), "cnt")],
    mode=AggMode.COMPLETE))

grp_expr = (Col("item") % Literal(4096, DataType.int32()))
grouped_plan = fuse_pipelines(HashAggregateExec(
    ProjectExec(fact_scan(), [(grp_expr, "g"), (Col("price"), "price"), (Col("qty"), "qty")]),
    keys=[(Col("g"), "g")],
    aggs=[(AggExpr(AggFn.SUM, Col("price")), "s"), (AggExpr(AggFn.MIN, Col("price")), "lo"),
          (AggExpr(AggFn.MAX, Col("price")), "hi"), (AggExpr(AggFn.AVG, Col("qty")), "aq")],
    mode=AggMode.COMPLETE))

for name, plan in [("join_agg", join_plan), ("grouped_agg", grouped_plan)]:
    run_plan(plan)  # warmup/compile
    with dispatch.counting() as c:
        t0 = time.perf_counter()
        run_plan(plan)
        t1 = time.perf_counter()
    print(f"{name}: {t1-t0:.3f}s  counts={c.counts}")

if os.environ.get("PROFILE"):
    import cProfile, pstats
    which = os.environ["PROFILE"]
    plan = join_plan if which == "join" else grouped_plan
    pr = cProfile.Profile()
    pr.enable()
    run_plan(plan)
    pr.disable()
    pstats.Stats(pr).sort_stats("cumulative").print_stats(40)

# ---- expr_chain + window shapes (bench parity) ----
if os.environ.get("EXTRA"):
    from blaze_tpu.exprs.ir import ScalarFn
    from blaze_tpu.ops.window import WindowExec, WindowFn
    from blaze_tpu.ops.sort import SortKey

    rev = Col("price") * Col("qty").cast(DataType.float32())
    score = ScalarFn("ln", (rev + Literal(1.0, DataType.float32()),)) * ScalarFn(
        "sqrt", (ScalarFn("abs", (Col("price") - Literal(50.0, DataType.float32()),)),))
    expr_plan = fuse_pipelines(HashAggregateExec(
        ProjectExec(fact_scan(), [(score.cast(DataType.float64()), "sc")]),
        keys=[],
        aggs=[(AggExpr(AggFn.SUM, Col("sc")), "s"), (AggExpr(AggFn.MAX, Col("sc")), "m")],
        mode=AggMode.COMPLETE))

    window_plan = HashAggregateExec(
        WindowExec(
            ProjectExec(fact_scan(), [(Col("part"), "part"), (Col("price"), "price")]),
            partition_by=[Col("part")],
            order_by=[SortKey(Col("price"), ascending=False)],
            functions=[WindowFn("row_number", None, "rk"),
                       WindowFn("sum", Col("price"), "run", frame=("rows", None, 0))]),
        keys=[],
        aggs=[(AggExpr(AggFn.SUM, Col("rk").cast(DataType.float64())), "rksum"),
              (AggExpr(AggFn.SUM, Col("run")), "runsum")],
        mode=AggMode.COMPLETE)

    for name, plan in [("expr_chain", expr_plan), ("window", window_plan)]:
        run_plan(plan)
        with dispatch.counting() as c:
            t0 = time.perf_counter()
            run_plan(plan)
            t1 = time.perf_counter()
        print(f"{name}: {t1-t0:.3f}s  counts={c.counts}")
    # numpy baselines
    t0 = time.perf_counter()
    r = price * qty.astype(np.float32)
    sc = (np.log(r + np.float32(1.0)) * np.sqrt(np.abs(price - np.float32(50.0)))).astype(np.float64)
    out = (float(sc.sum()), float(sc.max()))
    print(f"expr_chain numpy: {time.perf_counter()-t0:.3f}s")
    import pandas as pd
    fact_df = pd.DataFrame({"part": part_sk, "price": price})
    t0 = time.perf_counter()
    gsort = fact_df.sort_values(["part", "price"], ascending=[True, False]).groupby("part", sort=False)["price"]
    rk = gsort.cumcount() + 1
    run = gsort.cumsum()
    out = (float(rk.sum()), float(run.sum()))
    print(f"window pandas: {time.perf_counter()-t0:.3f}s")
