#!/usr/bin/env python
"""Standalone repro: jaxlib 0.9.0 CPU-client segfault under cumulative
compilation volume of LARGE MANY-OUTPUT programs in one process.

History (rounds 2-3 of this build): the full TPC-DS differential suite
run in a single process reliably dies with SIGSEGV inside
`backend_compile_and_load` after a few hundred query compilations. The
round-3 bisect (run_tests.py docstring) excluded:
  - thread concurrency        (BLAZE_TASK_THREADS=1 still crashes)
  - the engine's C++ tier     (BLAZE_DISABLE_NATIVE=1 still crashes)
  - executable eviction       (cache cap 0 + no clears still crash)
  - the legacy thunk runtime  (crashes later, same signature)
and a 3000-compile loop of SMALL programs survives - the trigger is
specifically large programs with MANY OUTPUTS (the engine's fused
aggregate kernels return dozens of state arrays) compiled at volume.

This script is that observation distilled: it compiles structurally
distinct many-output programs (default 96 outputs each, ~150 fused ops)
in a loop, printing progress per compile so the crash point is visible.
On this environment's jaxlib it is expected to die with SIGSEGV
(rc -11) before reaching the target count; on a fixed jaxlib it exits 0.

Usage:
    python benchmarks/jaxlib_segfault_repro.py [n_programs] [n_outputs]
    # defaults: 600 programs x 96 outputs; ~20-40 min on one core.
    # Survives? Raise n_programs; the suite crashed between ~200 and
    # ~500 structurally-distinct compilations.

Upgrade test: this image forbids pip installs, so "try jaxlib HEAD" is
documented as the exit rather than executed here. To run it elsewhere:
    python -m venv /tmp/v && . /tmp/v/bin/activate
    pip install -U jax jaxlib
    python benchmarks/jaxlib_segfault_repro.py
If a newer jaxlib survives, drop run_tests.py's process sharding and
record the single-process suite wall-clock.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def build_program(seed: int, n_outputs: int):
    """One structurally distinct many-output program shaped like the
    engine's fused aggregate kernels: elementwise chains + segment
    reductions fanning out to dozens of state arrays."""
    import jax
    import jax.numpy as jnp

    def fn(x, g):
        outs = []
        y = x
        for i in range(n_outputs):
            # vary structure per seed AND per output so nothing hits
            # the compilation cache
            k = (seed * 131 + i * 17) % 7
            y = y * (1.0 + 0.001 * k) + jnp.float32(i)
            if k % 3 == 0:
                y = jnp.where(y > 50.0, y - 25.0, y)
            s = jax.ops.segment_sum(
                y, g, num_segments=256 + (seed % 13)
            )
            outs.append(s)
            if k % 2 == 0:
                outs.append(jnp.sum(y) * jnp.float32(seed + 1))
        return outs

    return jax.jit(fn)


def main() -> int:
    n_programs = int(sys.argv[1]) if len(sys.argv) > 1 else 600
    n_outputs = int(sys.argv[2]) if len(sys.argv) > 2 else 96

    import numpy as np

    import jax
    import jax.numpy as jnp

    jax.config.update("jax_platforms", "cpu")
    print(
        f"jax {jax.__version__} jaxlib "
        f"{getattr(jax, 'lib', None) and jax.lib.__version__}; "
        f"{n_programs} programs x {n_outputs} outputs",
        flush=True,
    )
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.random(1 << 16).astype(np.float32))
    g = jnp.asarray(
        rng.integers(0, 256, 1 << 16).astype(np.int32)
    )
    for i in range(n_programs):
        fn = build_program(i, n_outputs)
        out = fn(x, g)
        jax.block_until_ready(out)
        del fn, out
        print(f"compiled {i + 1}/{n_programs}", flush=True)
    print("SURVIVED: no segfault at this volume", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
