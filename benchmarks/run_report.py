"""Multi-config benchmark report (BASELINE.json's five configs).

Runs each benchmark shape end-to-end through the engine on the available
accelerator and the same computation on CPU (numpy/pandas vectorized),
then writes a markdown report into benchmark-results/ - the reference
repo's reporting practice (benchmark-results/20220522.md).

Usage: python benchmarks/run_report.py [--rows N]
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import time

import numpy as np
import pandas as pd

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
import sys  # noqa: E402

sys.path.insert(0, REPO)


def gen_tables(n_rows: int, seed=7):
    rng = np.random.default_rng(seed)
    store_sales = pd.DataFrame(
        {
            "ss_sold_date_sk": rng.integers(0, 366, n_rows).astype(
                np.int32),
            "ss_item_sk": rng.integers(0, 2000, n_rows).astype(np.int32),
            "ss_customer_sk": rng.integers(0, 5000, n_rows).astype(
                np.int64),
            "ss_quantity": rng.integers(1, 100, n_rows).astype(np.int32),
            "ss_sales_price": (rng.random(n_rows) * 200).astype(
                np.float32),
            "ss_ext_sales_price": (rng.random(n_rows) * 2000).astype(
                np.float32),
        }
    )
    date_dim = pd.DataFrame(
        {
            "d_date_sk": np.arange(366, dtype=np.int32),
            "d_year": (1998 + np.arange(366) // 100).astype(np.int32),
            "d_moy": ((np.arange(366) // 30) % 12 + 1).astype(np.int32),
        }
    )
    return store_sales, date_dim


def timed(fn, warmup=1, iters=3):
    for _ in range(warmup):
        fn()
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn()
    return (time.perf_counter() - t0) / iters, out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=1_000_000)
    args = ap.parse_args()
    n = args.rows

    import jax

    jax.config.update("jax_enable_x64", True)

    from blaze_tpu.config import EngineConfig, set_config

    # big batches for accelerator benchmarking: fewer, larger dispatches
    set_config(
        EngineConfig(
            batch_size=1 << 20,
            shape_buckets=(256, 4096, 65536, 1 << 20),
        )
    )

    from blaze_tpu import ColumnBatch
    from blaze_tpu.exprs import AggExpr, AggFn, Col
    from blaze_tpu.ops import (
        AggMode,
        ExecContext,
        FilterExec,
        HashAggregateExec,
        MemoryScanExec,
        ProjectExec,
        ShuffleWriterExec,
        SortMergeJoinExec,
        JoinType,
    )
    from blaze_tpu.ops.fused import fuse_pipelines
    from blaze_tpu.runtime.executor import run_plan
    from blaze_tpu.types import DataType
    import pyarrow as pa
    import tempfile

    ss, dd = gen_tables(n)
    results = []

    def scan_of(df, parts=1):
        rb = pa.RecordBatch.from_pandas(df, preserve_index=False)
        per = (rb.num_rows + parts - 1) // parts
        partitions = []
        schema = None
        for p in range(parts):
            sl = rb.slice(p * per, min(per, rb.num_rows - p * per))
            cb = ColumnBatch.from_arrow(sl)
            schema = cb.schema
            partitions.append([cb] if sl.num_rows else [])
        return MemoryScanExec(partitions, schema)

    # ---- config 1: q6 scan+filter+project (also covered by bench.py) ----
    # scans are staged to device once; timings cover the compute path over
    # HBM-resident batches (per-iteration H2D through this harness's
    # network tunnel would measure the tunnel, not the engine)
    scan_ss = scan_of(ss)
    scan_dd = scan_of(dd)
    scan_dd_nov = scan_of(dd[dd.d_moy == 11])

    def q6_engine():
        plan = fuse_pipelines(
            HashAggregateExec(
                ProjectExec(
                    FilterExec(
                        scan_ss,
                        (Col("ss_sales_price") > 100.0)
                        & (Col("ss_quantity") < 50),
                    ),
                    [(Col("ss_sales_price")
                      * Col("ss_quantity").cast(DataType.float32()),
                      "rev")],
                ),
                keys=[],
                aggs=[(AggExpr(AggFn.SUM, Col("rev")), "t")],
                mode=AggMode.COMPLETE,
            )
        )
        return run_plan(plan)

    def q6_cpu():
        m = (ss.ss_sales_price.values > 100.0) & (
            ss.ss_quantity.values < 50
        )
        return float(
            (ss.ss_sales_price.values[m]
             * ss.ss_quantity.values[m]).sum()
        )

    te, _ = timed(q6_engine)
    tc, _ = timed(q6_cpu)
    results.append(("q6 scan+filter+project+agg", n, te, tc))
    print(f"[report] q6 done engine={te:.2f}s cpu={tc:.2f}s",
          file=sys.stderr, flush=True)

    # ---- config 2: q1-shaped grouped aggregate ----
    def q1_engine():
        plan = HashAggregateExec(
            scan_ss,
            keys=[(Col("ss_customer_sk"), "c")],
            aggs=[(AggExpr(AggFn.SUM, Col("ss_ext_sales_price")), "s")],
            mode=AggMode.COMPLETE,
        )
        return run_plan(plan)

    def q1_cpu():
        return ss.groupby("ss_customer_sk")["ss_ext_sales_price"].sum()

    te, _ = timed(q1_engine)
    tc, _ = timed(q1_cpu)
    results.append(("q1 grouped aggregate (5k groups)", n, te, tc))
    print(f"[report] q1 done engine={te:.2f}s cpu={tc:.2f}s",
          file=sys.stderr, flush=True)

    # ---- config 3: q3-shaped SMJ + aggregate ----
    dates = gen_tables(1)[1]

    def q3_engine():
        j = SortMergeJoinExec(
            scan_ss, scan_dd_nov,
            ["ss_sold_date_sk"], ["d_date_sk"], JoinType.INNER,
        )
        plan = HashAggregateExec(
            j,
            keys=[(Col("d_year"), "y"), (Col("ss_item_sk"), "i")],
            aggs=[(AggExpr(AggFn.SUM, Col("ss_ext_sales_price")), "s")],
            mode=AggMode.COMPLETE,
        )
        return run_plan(plan)

    def q3_cpu():
        mer = ss.merge(
            dd[dd.d_moy == 11], left_on="ss_sold_date_sk",
            right_on="d_date_sk",
        )
        return mer.groupby(["d_year", "ss_item_sk"])[
            "ss_ext_sales_price"
        ].sum()

    te, _ = timed(q3_engine, warmup=1, iters=2)
    tc, _ = timed(q3_cpu, warmup=1, iters=2)
    results.append(("q3 SMJ date_dim + grouped agg", n, te, tc))
    print(f"[report] q3 done engine={te:.2f}s cpu={tc:.2f}s",
          file=sys.stderr, flush=True)

    # ---- config 4: 200-way hash shuffle repartition ----
    tmp = tempfile.mkdtemp(prefix="blz-bench-")

    def shuffle_engine():
        op = ShuffleWriterExec(
            scan_ss, [Col("ss_customer_sk")], 200,
            os.path.join(tmp, "b.data"), os.path.join(tmp, "b.index"),
        )
        for _ in op.execute(0, ExecContext()):
            pass
        return True

    def shuffle_cpu():
        # numpy equivalent: murmur3 host hash + stable sort + slices
        from blaze_tpu.ops.shuffle_writer import _chain_fixed

        h = np.full(len(ss), 42, dtype=np.uint32)
        h = _chain_fixed(
            ss.ss_customer_sk.values, None, DataType.int64(), h
        )
        pid = (h.view(np.int32) % 200)
        pid = np.where(pid < 0, pid + 200, pid)
        order = np.argsort(pid, kind="stable")
        return order

    te, _ = timed(shuffle_engine, warmup=1, iters=2)
    tc, _ = timed(shuffle_cpu, warmup=1, iters=2)
    results.append(
        ("200-way murmur3 shuffle write (incl zstd IPC)", n, te, tc)
    )

    # ---- report ----
    backend = jax.default_backend()
    lines = [
        f"# blaze-tpu benchmark report - "
        f"{datetime.date.today().isoformat()}",
        "",
        f"rows={n:,}  backend={backend}  device={jax.devices()[0]}",
        "",
        "| config | engine (s) | cpu baseline (s) | engine rows/s |"
        " speedup |",
        "|---|---|---|---|---|",
    ]
    for name, rows, te, tc in results:
        lines.append(
            f"| {name} | {te:.3f} | {tc:.3f} | {rows/te:,.0f} |"
            f" {tc/te:.2f}x |"
        )
    # measure this harness's per-dispatch floor: one trivial kernel call
    # round trip (through the axon network tunnel this is ~70 ms; on
    # directly attached TPU it is ~100 us)
    import jax.numpy as jnp

    x = jnp.ones((8, 128), jnp.float32)
    f = jax.jit(lambda v: v.sum())
    np.asarray(f(x))
    t0 = time.perf_counter()
    for _ in range(5):
        np.asarray(f(x))
    rpc_floor = (time.perf_counter() - t0) / 5

    lines.append("")
    lines.append(
        f"Per-dispatch round-trip floor on this backend: "
        f"{rpc_floor*1000:.1f} ms (trivial kernel + scalar fetch)."
    )
    lines.append(
        "CPU baseline is the same computation as vectorized numpy/pandas "
        "in this process (single core). Engine timings include host<->"
        "device transfers and, for the shuffle, zstd Arrow-IPC encoding "
        "and file assembly. NOTE: in this harness the chip sits behind a "
        "network RPC tunnel, so multi-dispatch queries at this row count "
        "measure dispatch latency, not the engine - each query above "
        "issues ~20-40 dispatches. The dispatch-amortized kernel "
        "throughput (bench.py, one fused dispatch) is ~4.3B rows/s on "
        "this chip, ~50x the CPU baseline; on directly attached TPU "
        "hardware the per-dispatch floor drops ~700x and these "
        "end-to-end numbers follow it."
    )
    out_dir = os.path.join(REPO, "benchmark-results")
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(
        out_dir, f"{datetime.date.today().strftime('%Y%m%d')}-{backend}.md"
    )
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")
    print("\n".join(lines))
    print(f"\nwritten: {path}")


if __name__ == "__main__":
    main()
