"""Multi-config benchmark report (BASELINE's five configs) through the
PRODUCTION path: each query is serialized to a TaskDefinition and run by
runtime/executor.execute_task - plan decode, fusion, device compute,
Arrow boundary - including IO, with per-query device round-trip counts
(runtime/dispatch.py) logged alongside wall-clock. This mirrors the
reference repo's reporting practice (benchmark-results/20220522.md) where
every number flows through the real task entry (exec.rs:118).

CPU baseline per config: the same computation in vectorized
numpy/pandas AND (where expressible) pyarrow.compute; the faster is the
denominator. This host has one CPU core - the reference's DataFusion
engine is likewise single-threaded per task.

Usage: python benchmarks/run_report.py [--rows N]
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import sys
import tempfile
import time

import numpy as np
import pandas as pd

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def gen_tables(n_rows: int, seed=7):
    rng = np.random.default_rng(seed)
    store_sales = pd.DataFrame(
        {
            "ss_sold_date_sk": rng.integers(0, 366, n_rows).astype(
                np.int32),
            "ss_item_sk": rng.integers(0, 2000, n_rows).astype(np.int32),
            "ss_customer_sk": rng.integers(0, 5000, n_rows).astype(
                np.int64),
            "ss_quantity": rng.integers(1, 100, n_rows).astype(np.int32),
            "ss_sales_price": (rng.random(n_rows) * 200).astype(
                np.float32),
            "ss_ext_sales_price": (rng.random(n_rows) * 2000).astype(
                np.float32),
        }
    )
    date_dim = pd.DataFrame(
        {
            "d_date_sk": np.arange(366, dtype=np.int32),
            "d_year": (1998 + np.arange(366) // 100).astype(np.int32),
            "d_moy": ((np.arange(366) // 30) % 12 + 1).astype(np.int32),
        }
    )
    return store_sales, date_dim


def timed(fn, warmup=1, iters=3):
    for _ in range(warmup):
        out = fn()
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn()
    return (time.perf_counter() - t0) / iters, out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=4_000_000)
    args = ap.parse_args()
    n = args.rows

    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    from blaze_tpu.config import EngineConfig, set_config

    # big batches: fewer, larger dispatches (the accelerator operating
    # point; through a network-tunneled chip each dispatch is an RTT)
    set_config(
        EngineConfig(
            batch_size=max(n, 1 << 20),
            shape_buckets=(256, 4096, 65536, 1 << 20, max(n, 1 << 20)),
        )
    )

    from blaze_tpu.exprs import AggExpr, AggFn, Col
    from blaze_tpu.ops import (
        AggMode,
        ExecContext,
        FilterExec,
        HashAggregateExec,
        HashJoinExec,
        JoinType,
        ProjectExec,
        ShuffleWriterExec,
        SortMergeJoinExec,
    )
    from blaze_tpu.ops.memory_scan import MemoryScanExec
    from blaze_tpu.plan.serde import task_to_proto
    from blaze_tpu.runtime import dispatch
    from blaze_tpu.runtime.executor import execute_task
    from blaze_tpu.batch import ColumnBatch
    from blaze_tpu.types import DataType
    import pyarrow as pa
    import pyarrow.parquet as pq

    ss, dd = gen_tables(n)
    dd_nov = dd[dd.d_moy == 11]

    # parquet inputs (IO included in engine timings via ParquetScanExec)
    tmp = tempfile.mkdtemp(prefix="blz-bench-")
    ss_path = os.path.join(tmp, "store_sales.parquet")
    dd_path = os.path.join(tmp, "date_dim.parquet")
    pq.write_table(
        pa.Table.from_pandas(ss, preserve_index=False), ss_path,
        compression="zstd",
    )
    pq.write_table(
        pa.Table.from_pandas(dd_nov, preserve_index=False), dd_path,
        compression="zstd",
    )

    from blaze_tpu.ops.parquet_scan import FileRange, ParquetScanExec

    def scan_ss():
        return ParquetScanExec([[FileRange(ss_path)]])

    def scan_dd():
        return ParquetScanExec([[FileRange(dd_path)]])

    # device-staged variants (compute-path timings, H2D excluded)
    cb_ss = ColumnBatch.from_arrow(
        pa.RecordBatch.from_pandas(ss, preserve_index=False)
    )
    cb_dd = ColumnBatch.from_arrow(
        pa.RecordBatch.from_pandas(dd_nov, preserve_index=False)
    )

    def mem_ss():
        return MemoryScanExec([[cb_ss]], cb_ss.schema)

    def mem_dd():
        return MemoryScanExec([[cb_dd]], cb_dd.schema)

    results = []

    def run_config(name, plan_builder, cpu_fns):
        """Time the serialized-task path (incl IO) + the staged path,
        and the best CPU baseline."""
        blob = task_to_proto(plan_builder(scan_ss, scan_dd), 0)

        def engine():
            return sum(rb.num_rows for rb in execute_task(blob))

        t_engine, out_rows = timed(engine)
        with dispatch.counting() as c:
            engine()
        counts = c.counts

        # staged variant: MemoryScan holds live device arrays (not
        # proto-serializable, like the reference's in-memory inputs), so
        # drive the executor directly
        from blaze_tpu.ops.fused import fuse_pipelines
        from blaze_tpu.runtime.executor import execute_partition

        plan_mem = fuse_pipelines(plan_builder(mem_ss, mem_dd))

        def engine_staged():
            return sum(
                rb.num_rows
                for rb in execute_partition(plan_mem, 0, ExecContext())
            )

        t_staged, _ = timed(engine_staged)

        t_cpu = min(timed(f)[0] for f in cpu_fns)
        results.append(
            (name, t_engine, t_staged, t_cpu, counts, out_rows)
        )
        print(
            f"[report] {name}: engine={t_engine:.3f}s "
            f"staged={t_staged:.3f}s cpu={t_cpu:.3f}s "
            f"roundtrips={counts}",
            file=sys.stderr, flush=True,
        )

    # ---- config 1: q6 scan+filter+project+global agg ----
    def q6_plan(s_ss, s_dd):
        return HashAggregateExec(
            ProjectExec(
                FilterExec(
                    s_ss(),
                    (Col("ss_sales_price") > 100.0)
                    & (Col("ss_quantity") < 50),
                ),
                [(Col("ss_sales_price")
                  * Col("ss_quantity").cast(DataType.float32()),
                  "rev")],
            ),
            keys=[],
            aggs=[(AggExpr(AggFn.SUM, Col("rev")), "t")],
            mode=AggMode.COMPLETE,
        )

    def q6_cpu():
        m = (ss.ss_sales_price.values > 100.0) & (
            ss.ss_quantity.values < 50
        )
        return float(
            (ss.ss_sales_price.values[m]
             * ss.ss_quantity.values[m]).sum()
        )

    run_config("q6 scan+filter+project+agg", q6_plan, [q6_cpu])

    # ---- config 2: q1-shaped grouped aggregate ----
    def q1_plan(s_ss, s_dd):
        return HashAggregateExec(
            s_ss(),
            keys=[(Col("ss_customer_sk"), "c")],
            aggs=[(AggExpr(AggFn.SUM, Col("ss_ext_sales_price")), "s")],
            mode=AggMode.COMPLETE,
        )

    def q1_cpu():
        return ss.groupby("ss_customer_sk")["ss_ext_sales_price"].sum()

    run_config("q1 grouped aggregate (5k groups)", q1_plan, [q1_cpu])

    # ---- config 3: q3-shaped SMJ + grouped aggregate ----
    def q3_plan(s_ss, s_dd):
        j = SortMergeJoinExec(
            s_ss(), s_dd(),
            ["ss_sold_date_sk"], ["d_date_sk"], JoinType.INNER,
        )
        return HashAggregateExec(
            j,
            keys=[(Col("d_year"), "y"), (Col("ss_item_sk"), "i")],
            aggs=[(AggExpr(AggFn.SUM, Col("ss_ext_sales_price")), "s")],
            mode=AggMode.COMPLETE,
        )

    def q3_cpu():
        mer = ss.merge(
            dd_nov, left_on="ss_sold_date_sk", right_on="d_date_sk",
        )
        return mer.groupby(["d_year", "ss_item_sk"])[
            "ss_ext_sales_price"
        ].sum()

    run_config("q3 SMJ date_dim + grouped agg", q3_plan, [q3_cpu])

    # ---- config 4: broadcast hash join + agg (BHJ tier) ----
    def bhj_plan(s_ss, s_dd):
        j = HashJoinExec(
            s_dd(), s_ss(),
            ["d_date_sk"], ["ss_sold_date_sk"], JoinType.INNER,
        )
        return HashAggregateExec(
            j,
            keys=[(Col("d_year"), "y")],
            aggs=[(AggExpr(AggFn.AVG, Col("ss_sales_price")), "a")],
            mode=AggMode.COMPLETE,
        )

    def bhj_cpu():
        mer = ss.merge(
            dd_nov, left_on="ss_sold_date_sk", right_on="d_date_sk",
        )
        return mer.groupby("d_year")["ss_sales_price"].mean()

    run_config("q2 BHJ date_dim + avg", bhj_plan, [bhj_cpu])

    # ---- config 5: 200-way hash shuffle write (incl zstd IPC) ----
    shuffle_tmp = tempfile.mkdtemp(prefix="blz-shuf-")

    def shuffle_plan(s_ss, s_dd):
        return ShuffleWriterExec(
            s_ss(), [Col("ss_customer_sk")], 200,
            os.path.join(shuffle_tmp, "b.data"),
            os.path.join(shuffle_tmp, "b.index"),
        )

    def shuffle_cpu():
        from blaze_tpu.ops.shuffle_writer import _chain_fixed

        h = np.full(len(ss), 42, dtype=np.uint32)
        h = _chain_fixed(
            ss.ss_customer_sk.values, None, DataType.int64(), h
        )
        pid = (h.view(np.int32) % 200)
        pid = np.where(pid < 0, pid + 200, pid)
        order = np.argsort(pid, kind="stable")
        # materialize the scattered payload (what the engine writes)
        return [c.values[order] for _, c in ss.items()]

    run_config(
        "200-way murmur3 shuffle write (incl zstd IPC)",
        shuffle_plan, [shuffle_cpu],
    )

    # ---- report ----
    backend = jax.default_backend()
    import jax.numpy as jnp

    x = jnp.ones((8, 128), jnp.float32)
    f = jax.jit(lambda v: v.sum())
    np.asarray(f(x))
    t0 = time.perf_counter()
    for _ in range(5):
        np.asarray(f(x))
    rpc_floor = (time.perf_counter() - t0) / 5

    lines = [
        f"# blaze-tpu benchmark report - "
        f"{datetime.date.today().isoformat()}",
        "",
        f"rows={n:,}  backend={backend}  device={jax.devices()[0]}  "
        f"dispatch-floor={rpc_floor*1000:.1f}ms",
        "",
        "All engine timings run through `execute_task` (serialized "
        "TaskDefinition -> decode -> fuse -> execute -> Arrow out). "
        "`engine` includes parquet decode + H2D; `staged` starts from "
        "device-resident columns. `roundtrips` counts device dispatches "
        "+ blocking syncs + batched fetches per query "
        "(runtime/dispatch.py).",
        "",
        "| config | engine incl IO (s) | staged (s) | cpu (s) | "
        "engine rows/s | vs cpu (incl IO) | vs cpu (staged) | "
        "roundtrips |",
        "|---|---|---|---|---|---|---|---|",
    ]
    def roundtrips(counts):
        # the ONE definition of a device round trip for both the md
        # table and trend.csv - two copies would drift
        return sum(
            v for k, v in counts.items()
            if k in ("dispatches", "d2h_syncs", "d2h_fetches")
        )

    for name, te, ts, tc, counts, _ in results:
        rt = roundtrips(counts)
        lines.append(
            f"| {name} | {te:.3f} | {ts:.3f} | {tc:.3f} | {n/te:,.0f} |"
            f" {tc/te:.2f}x | {tc/ts:.2f}x | {rt} ({counts}) |"
        )
    lines.append("")
    lines.append(
        "CPU baseline: same computation, vectorized numpy/pandas (and "
        "pyarrow.compute where applicable), single core - this host has "
        "1 CPU; the reference's DataFusion engine is also one thread "
        "per task."
    )
    out_dir = os.path.join(REPO, "benchmark-results")
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(
        out_dir, f"{datetime.date.today().strftime('%Y%m%d')}-{backend}.md"
    )
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")
    print("\n".join(lines))
    print(f"\nwritten: {path}")

    # cross-round trend artifact (VERDICT r3 item 10): one CSV row per
    # config per run, appended forever - the analog of the reference's
    # benchmark-results/ history, so a perf regression between rounds
    # is a diff in one file instead of a by-hand comparison of MDs
    import csv
    import subprocess

    try:
        commit = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], cwd=REPO,
            capture_output=True, text=True, timeout=10,
        ).stdout.strip() or "unknown"
    except Exception:  # noqa: BLE001
        commit = "unknown"
    trend = os.path.join(out_dir, "trend.csv")
    new_file = not os.path.exists(trend)
    with open(trend, "a", newline="") as f:
        w = csv.writer(f)
        if new_file:
            w.writerow(
                ["date", "commit", "backend", "rows", "config",
                 "engine_s", "cpu_best_s", "vs_cpu",
                 "device_roundtrips"]
            )
        for name, te, ts, tc, counts, _ in results:
            rt = roundtrips(counts)
            w.writerow(
                [datetime.date.today().isoformat(), commit, backend,
                 n, name, round(te, 4), round(tc, 4),
                 round(tc / te, 3), rt]
            )
    print(f"trend appended: {trend}")


if __name__ == "__main__":
    main()
